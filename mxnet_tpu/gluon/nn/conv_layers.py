"""Gluon convolution / pooling layers.

Reference: ``python/mxnet/gluon/nn/conv_layers.py``. Same API; the Convolution
op lowers to ``lax.conv_general_dilated`` which XLA tiles onto the MXU.
NCHW is the reference default layout and is accepted everywhere; NHWC is
TPU-preferred and supported via ``layout=``.
"""
from __future__ import annotations

from ...base import MXNetError
from ... import layout as layout_mod
from ..block import HybridBlock
from .activations import Activation


def _pair(x, n):
    if isinstance(x, (tuple, list)):
        return tuple(x)
    return (x,) * n


class _Conv(HybridBlock):
    """Shared conv implementation (parity: conv_layers.py _Conv)."""

    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 op_name="Convolution", adj=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._channels = channels
        self._in_channels = in_channels
        nd = len(kernel_size)
        if layout is None:
            # layout policy (layout.py): channel-first unless an explicit
            # channels-last policy/scope is active.  Deconvolution lowers
            # channel-first only, so transposed convs pin their default.
            layout = layout_mod.default_layout(nd)
        strides = _pair(strides, nd)
        padding = _pair(padding, nd)
        dilation = _pair(dilation, nd)
        self._op_name = op_name
        self._kwargs = {
            "kernel": kernel_size, "stride": strides, "dilate": dilation,
            "pad": padding, "num_filter": channels, "num_group": groups,
            "no_bias": not use_bias, "layout": layout}
        if adj is not None:
            self._kwargs["adj"] = adj
        self._layout = layout
        self._groups = groups
        self._kernel_size = kernel_size

        with self.name_scope():
            wshape = self._weight_shape(in_channels)
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer,
                    allow_deferred_init=True)
            self.act = Activation(activation, prefix=activation + "_") \
                if activation is not None else None

    def _weight_shape(self, in_channels):
        # OIHW for channel-first layouts, HWIO for channel-last (TPU native)
        k = tuple(self._kernel_size)
        if self._layout.startswith("NC") or self._layout in ("NCW",):
            if self._op_name == "Deconvolution":
                return (in_channels, self._channels // self._groups) + k
            return (self._channels, in_channels // self._groups
                    if in_channels else 0) + k
        if self._op_name == "Deconvolution":
            return k + (self._channels // self._groups, in_channels)
        return k + (in_channels // self._groups if in_channels else 0,
                    self._channels)

    def _channel_axis(self):
        return 1 if self._layout.startswith("NC") else -1

    def _shape_hint(self, x, *args):
        shape = self.weight.shape
        if shape and 0 in shape:
            in_channels = x.shape[self._channel_axis()]
            self._in_channels = in_channels
            self.weight.shape = self._weight_shape(in_channels)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = getattr(F, self._op_name)(x, weight, bias, **self._kwargs)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        return "%s(%s, kernel_size=%s, stride=%s, layout=%s)" % (
            self.__class__.__name__, self._channels,
            self._kwargs["kernel"], self._kwargs["stride"], self._layout)


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout=None, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,)
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout=None, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * 2
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout=None, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * 3
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,)
        if isinstance(output_padding, int):
            output_padding = (output_padding,)
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution", adj=output_padding, **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * 2
        if isinstance(output_padding, int):
            output_padding = (output_padding,) * 2
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution", adj=output_padding, **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * 3
        if isinstance(output_padding, int):
            output_padding = (output_padding,) * 3
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution", adj=output_padding, **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, global_pool, pool_type,
                 layout, count_include_pad=None, ceil_mode=False, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        if strides is None:
            strides = pool_size
        nd = len(pool_size)
        if layout is None:
            layout = layout_mod.default_layout(nd)
        self._kwargs = {
            "kernel": pool_size, "stride": _pair(strides, nd),
            "pad": _pair(padding, nd), "global_pool": global_pool,
            "pool_type": pool_type, "layout": layout,
            "pooling_convention": "full" if ceil_mode else "valid"}
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def _alias(self):
        return "pool"

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)

    def __repr__(self):
        return "%s(size=%s, stride=%s, padding=%s)" % (
            self.__class__.__name__, self._kwargs["kernel"],
            self._kwargs["stride"], self._kwargs["pad"])


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout=None,
                 ceil_mode=False, **kwargs):
        if isinstance(pool_size, int):
            pool_size = (pool_size,)
        super().__init__(pool_size, strides, padding, False, "max", layout,
                         ceil_mode=ceil_mode, **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout=None, ceil_mode=False, **kwargs):
        if isinstance(pool_size, int):
            pool_size = (pool_size,) * 2
        super().__init__(pool_size, strides, padding, False, "max", layout,
                         ceil_mode=ceil_mode, **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout=None, ceil_mode=False, **kwargs):
        if isinstance(pool_size, int):
            pool_size = (pool_size,) * 3
        super().__init__(pool_size, strides, padding, False, "max", layout,
                         ceil_mode=ceil_mode, **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout=None,
                 ceil_mode=False, count_include_pad=True, **kwargs):
        if isinstance(pool_size, int):
            pool_size = (pool_size,)
        super().__init__(pool_size, strides, padding, False, "avg", layout,
                         count_include_pad, ceil_mode, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout=None, ceil_mode=False, count_include_pad=True,
                 **kwargs):
        if isinstance(pool_size, int):
            pool_size = (pool_size,) * 2
        super().__init__(pool_size, strides, padding, False, "avg", layout,
                         count_include_pad, ceil_mode, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout=None, ceil_mode=False, count_include_pad=True,
                 **kwargs):
        if isinstance(pool_size, int):
            pool_size = (pool_size,) * 3
        super().__init__(pool_size, strides, padding, False, "avg", layout,
                         count_include_pad, ceil_mode, **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1,), None, 0, True, "max", layout, **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1, 1), None, 0, True, "max", layout, **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1, 1, 1), None, 0, True, "max", layout, **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1,), None, 0, True, "avg", layout, **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1, 1), None, 0, True, "avg", layout, **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1, 1, 1), None, 0, True, "avg", layout, **kwargs)


class ReflectionPad2D(HybridBlock):
    """Parity: nn.ReflectionPad2D."""

    def __init__(self, padding=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        if len(padding) != 8:
            raise MXNetError("padding must be int or length-8 tuple")
        self._padding = tuple(padding)

    def hybrid_forward(self, F, x):
        return F.pad(x, mode="reflect", pad_width=self._padding)
