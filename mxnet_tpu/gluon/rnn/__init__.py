"""Gluon recurrent API (parity: ``python/mxnet/gluon/rnn/__init__.py``).

Cells step-by-step (rnn_cell.py), fused layers on ``lax.scan``
(rnn_layer.py).
"""
from .rnn_cell import *  # noqa: F401,F403
from .rnn_layer import *  # noqa: F401,F403
