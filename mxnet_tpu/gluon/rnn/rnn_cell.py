"""Recurrent cells (parity: ``python/mxnet/gluon/rnn/rnn_cell.py``).

Single-step cells plus combinators (sequential, bidirectional, residual,
zoneout, dropout).  ``unroll`` runs the Python time loop; under
``hybridize()`` the whole unrolled graph is traced into ONE XLA executable,
so the per-step matmuls pipeline on the MXU.  For long sequences prefer the
fused ``rnn.RNN/LSTM/GRU`` layers (rnn_layer.py) whose time loop is a
``lax.scan`` — constant compile time in sequence length.
"""
from __future__ import annotations

from ... import ndarray as nd_mod
from ..block import Block, HybridBlock

__all__ = ['RecurrentCell', 'HybridRecurrentCell', 'RNNCell', 'LSTMCell',
           'GRUCell', 'SequentialRNNCell', 'HybridSequentialRNNCell',
           'DropoutCell', 'ModifierCell', 'ZoneoutCell', 'ResidualCell',
           'BidirectionalCell']


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _get_begin_state(cell, F, begin_state, inputs, batch_size):
    if begin_state is None:
        begin_state = cell.begin_state(func=F.zeros, batch_size=batch_size)
    return begin_state


def _format_sequence(length, inputs, layout, merge, in_layout=None):
    """Normalize inputs to a list of (N, C) steps or a merged tensor.

    Returns (F, inputs, axis, batch_size) like the reference
    (rnn_cell.py:53); F is always the nd namespace here because hybridize
    is trace-based in this framework.
    """
    assert inputs is not None, \
        "unroll(inputs=None) is not supported; pass an NDArray or list"
    axis = layout.find('T')
    batch_axis = layout.find('N')
    F = nd_mod
    if isinstance(inputs, (list, tuple)):
        length = length or len(inputs)
        batch_size = inputs[0].shape[batch_axis]
        if merge is True:
            inputs = F.stack(*inputs, axis=axis)
    else:
        batch_size = inputs.shape[batch_axis]
        if merge is False:
            in_axis = (in_layout or layout).find('T')
            if length is None:
                length = inputs.shape[in_axis]
            assert length == inputs.shape[in_axis], \
                "length %s does not match time dim %s" % (
                    length, inputs.shape[in_axis])
            inputs = F.split(inputs, num_outputs=length, axis=in_axis,
                             squeeze_axis=True)
            if length == 1:
                inputs = [inputs]
    return F, inputs, axis, batch_size


def _mask_sequence_variable_length(F, data, length, valid_length, time_axis,
                                   merge):
    assert valid_length is not None
    if not isinstance(data, (list, tuple)):
        outputs = F.SequenceMask(data, sequence_length=valid_length,
                                 use_sequence_length=True, axis=time_axis)
    else:
        outputs = F.SequenceMask(F.stack(*data, axis=time_axis),
                                 sequence_length=valid_length,
                                 use_sequence_length=True, axis=time_axis)
        if not merge:
            outputs = F.split(outputs, num_outputs=length, axis=time_axis,
                              squeeze_axis=True)
            if length == 1:
                outputs = [outputs]
    return outputs


def _reverse_sequences(sequences, unroll_step, valid_length=None):
    F = nd_mod
    if valid_length is None:
        reversed_sequences = list(reversed(sequences))
    else:
        reversed_sequences = F.SequenceReverse(
            F.stack(*sequences, axis=0), sequence_length=valid_length,
            use_sequence_length=True)
        if unroll_step > 1:
            reversed_sequences = F.split(reversed_sequences,
                                         num_outputs=unroll_step, axis=0,
                                         squeeze_axis=True)
        else:
            reversed_sequences = [reversed_sequences]
    return reversed_sequences


class RecurrentCell(Block):
    """Abstract single-step recurrent cell (parity: rnn_cell.py:125)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        """Reset the step counter used to name begin-state arrays."""
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial states, one array per entry of ``state_info``."""
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called " \
            "directly. Call the modifier cell instead."
        if func is None:
            func = nd_mod.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            if info is not None:
                info = dict(info)
                info.update(kwargs)
            else:
                info = kwargs
            shape = info.pop('shape')
            info.pop('__layout__', None)
            states.append(func(shape, **info))
        return states

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None, valid_length=None):
        """Unroll the cell ``length`` steps (parity: rnn_cell.py:205).

        The Python loop is traced; hybridized parents compile it into one
        executable.
        """
        self.reset()
        F, inputs, axis, batch_size = _format_sequence(
            length, inputs, layout, False)
        begin_state = _get_begin_state(self, F, begin_state, inputs,
                                       batch_size)

        states = begin_state
        outputs = []
        all_states = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            states = [F.SequenceLast(F.stack(*ele_list, axis=0),
                                     sequence_length=valid_length,
                                     use_sequence_length=True, axis=0)
                      for ele_list in zip(*all_states)]
            outputs = _mask_sequence_variable_length(
                F, outputs, length, valid_length, axis, True)
        _, outputs, _, _ = _format_sequence(length, outputs, layout,
                                            merge_outputs)
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        return super().__call__(inputs, states)

    def forward(self, inputs, states):  # pragma: no cover - abstract
        raise NotImplementedError


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """Recurrent cell whose step is expressed via ``hybrid_forward``."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def forward(self, inputs, states):
        single = isinstance(states, nd_mod.NDArray)
        if single:
            states = [states]
        out, new_states = self._forward_imperative(inputs, states)
        return out, new_states

    def hybrid_forward(self, F, x, *args, **kwargs):  # pragma: no cover
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    """Elman RNN cell: h' = act(W_x x + b_x + W_h h + b_h)
    (parity: rnn_cell.py:327)."""

    def __init__(self, hidden_size, activation='tanh',
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer='zeros', h2h_bias_initializer='zeros',
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                'i2h_weight', shape=(hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                'h2h_weight', shape=(hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                'i2h_bias', shape=(hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                'h2h_bias', shape=(hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{'shape': (batch_size, self._hidden_size),
                 '__layout__': 'NC'}]

    def _alias(self):
        return 'rnn'

    def _shape_hint(self, inputs, states):
        if self.i2h_weight.shape and self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (self._hidden_size, inputs.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = self._get_activation(F, i2h + h2h, self._activation)
        return output, [output]

    def __repr__(self):
        shape = self.i2h_weight.shape
        return '%s(%s -> %s, %s)' % (self.__class__.__name__,
                                     shape[1] if shape else 0, shape[0],
                                     self._activation)


class LSTMCell(HybridRecurrentCell):
    """LSTM cell, gate order (i, f, g, o) matching the reference's
    fused kernels (parity: rnn_cell.py:428; gates rnn-inl.h)."""

    def __init__(self, hidden_size,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer='zeros', h2h_bias_initializer='zeros',
                 input_size=0, prefix=None, params=None,
                 activation='tanh', recurrent_activation='sigmoid'):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._activation = activation
        self._recurrent_activation = recurrent_activation
        with self.name_scope():
            self.i2h_weight = self.params.get(
                'i2h_weight', shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                'h2h_weight', shape=(4 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                'i2h_bias', shape=(4 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                'h2h_bias', shape=(4 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{'shape': (batch_size, self._hidden_size),
                 '__layout__': 'NC'},
                {'shape': (batch_size, self._hidden_size),
                 '__layout__': 'NC'}]

    def _alias(self):
        return 'lstm'

    def _shape_hint(self, inputs, states):
        if self.i2h_weight.shape and self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (4 * self._hidden_size, inputs.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slice_gates = F.SliceChannel(gates, num_outputs=4)
        in_gate = self._get_activation(F, slice_gates[0],
                                       self._recurrent_activation)
        forget_gate = self._get_activation(F, slice_gates[1],
                                           self._recurrent_activation)
        in_transform = self._get_activation(F, slice_gates[2],
                                            self._activation)
        out_gate = self._get_activation(F, slice_gates[3],
                                        self._recurrent_activation)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * self._get_activation(F, next_c, self._activation)
        return next_h, [next_h, next_c]

    def __repr__(self):
        shape = self.i2h_weight.shape
        return '%s(%s -> %s)' % (self.__class__.__name__,
                                 shape[1] if shape else 0, shape[0] // 4)


class GRUCell(HybridRecurrentCell):
    """GRU cell, gate order (r, z, n); reset gate applied to the h2h
    new-memory term — matching the reference/cuDNN convention
    (parity: rnn_cell.py:554)."""

    def __init__(self, hidden_size,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer='zeros', h2h_bias_initializer='zeros',
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                'i2h_weight', shape=(3 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                'h2h_weight', shape=(3 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                'i2h_bias', shape=(3 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                'h2h_bias', shape=(3 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{'shape': (batch_size, self._hidden_size),
                 '__layout__': 'NC'}]

    def _alias(self):
        return 'gru'

    def _shape_hint(self, inputs, states):
        if self.i2h_weight.shape and self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (3 * self._hidden_size, inputs.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_state_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_state_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h = F.SliceChannel(i2h, num_outputs=3)
        h2h_r, h2h_z, h2h = F.SliceChannel(h2h, num_outputs=3)
        reset_gate = F.Activation(i2h_r + h2h_r, act_type='sigmoid')
        update_gate = F.Activation(i2h_z + h2h_z, act_type='sigmoid')
        next_h_tmp = F.Activation(i2h + reset_gate * h2h, act_type='tanh')
        ones = F.ones_like(update_gate)
        next_h = (ones - update_gate) * next_h_tmp \
            + update_gate * prev_state_h
        return next_h, [next_h]

    def __repr__(self):
        shape = self.i2h_weight.shape
        return '%s(%s -> %s)' % (self.__class__.__name__,
                                 shape[1] if shape else 0, shape[0] // 3)


class SequentialRNNCell(RecurrentCell):
    """Stack cells; each step runs them in order (parity: rnn_cell.py:682)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def __repr__(self):
        return '%s(\n%s\n)' % (
            self.__class__.__name__,
            '\n'.join('(%s): %r' % (i, c)
                      for i, c in enumerate(self._children.values())))

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(),
                                  batch_size=batch_size, func=func, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        assert all(not isinstance(cell, BidirectionalCell)
                   for cell in self._children.values()), \
            "BidirectionalCell is only supported as the top-most cell"
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None, valid_length=None):
        self.reset()
        F, inputs, _, batch_size = _format_sequence(length, inputs, layout,
                                                    None)
        num_cells = len(self._children)
        begin_state = _get_begin_state(self, F, begin_state, inputs,
                                       batch_size)
        p = 0
        next_states = []
        for i, cell in enumerate(self._children.values()):
            n = len(cell.state_info())
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs,
                valid_length=valid_length)
            next_states.extend(states)
        return inputs, next_states

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)

    def forward(self, *args):  # pragma: no cover
        raise NotImplementedError


class HybridSequentialRNNCell(HybridRecurrentCell):
    """Hybridizable sequential stack (parity: rnn_cell.py:760)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def __repr__(self):
        return '%s(\n%s\n)' % (
            self.__class__.__name__,
            '\n'.join('(%s): %r' % (i, c)
                      for i, c in enumerate(self._children.values())))

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(),
                                  batch_size=batch_size, func=func, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        assert all(not isinstance(cell, BidirectionalCell)
                   for cell in self._children.values())
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None, valid_length=None):
        return SequentialRNNCell.unroll(
            self, length, inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs, valid_length=valid_length)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)


class DropoutCell(HybridRecurrentCell):
    """Apply dropout on input (parity: rnn_cell.py:835)."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        assert isinstance(rate, (int, float))
        self._rate = rate
        self._axes = axes

    def __repr__(self):
        return '%s(rate=%s, axes=%s)' % (self.__class__.__name__,
                                         self._rate, self._axes)

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return 'dropout'

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None, valid_length=None):
        self.reset()
        F, inputs, _, _ = _format_sequence(length, inputs, layout,
                                           merge_outputs)
        if isinstance(inputs, nd_mod.NDArray):
            return self.hybrid_forward(F, inputs, begin_state or [])
        return super().unroll(
            length, inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs, valid_length=valid_length)


class ModifierCell(HybridRecurrentCell):
    """Base for cells that wrap another cell and modify its computation
    (parity: rnn_cell.py:890).  The wrapped cell's parameters are owned by
    the wrapped cell; the modifier holds no parameters of its own."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified. One cell cannot be modified " \
            "twice" % base_cell.name
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size=batch_size, func=func,
                                           **kwargs)
        self.base_cell._modified = True
        return begin

    def hybrid_forward(self, F, inputs, states):  # pragma: no cover
        raise NotImplementedError

    def __repr__(self):
        return '%s(%r)' % (self.__class__.__name__, self.base_cell)


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (Krueger et al.) — randomly preserve previous
    state values (parity: rnn_cell.py:932)."""

    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.):
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout. " \
            "Please add ZoneoutCell to the cells underneath instead."
        assert not isinstance(base_cell, SequentialRNNCell) or not any(
            isinstance(c, BidirectionalCell)
            for c in base_cell._children.values()), \
            "SequentialRNNCell containing a BidirectionalCell doesn't " \
            "support zoneout."
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def __repr__(self):
        return '%s(p_out=%s, p_state=%s, %r)' % (
            self.__class__.__name__, self.zoneout_outputs,
            self.zoneout_states, self.base_cell)

    def _alias(self):
        return 'zoneout'

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell, p_outputs, p_states = (self.base_cell, self.zoneout_outputs,
                                     self.zoneout_states)
        next_output, next_states = cell(inputs, states)
        mask = (lambda p, like: F.Dropout(F.ones_like(like), p=p))
        prev_output = self._prev_output
        if prev_output is None:
            prev_output = F.zeros_like(next_output)
        output = (F.where(mask(p_outputs, next_output), next_output,
                          prev_output)
                  if p_outputs != 0. else next_output)
        states = ([F.where(mask(p_states, new_s), new_s, old_s)
                   for new_s, old_s in zip(next_states, states)]
                  if p_states != 0. else next_states)
        self._prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    """Add residual connection: output = base(input) + input
    (parity: rnn_cell.py:977)."""

    def __init__(self, base_cell):
        super().__init__(base_cell)

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None, valid_length=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs, valid_length=valid_length)
        self.base_cell._modified = True
        merge_outputs = (isinstance(outputs, nd_mod.NDArray)
                         if merge_outputs is None else merge_outputs)
        F, inputs, _, _ = _format_sequence(length, inputs, layout,
                                           merge_outputs)
        if valid_length is not None:
            axis = layout.find('T')
            inputs = _mask_sequence_variable_length(F, inputs, length,
                                                    valid_length, axis,
                                                    merge_outputs)
        if merge_outputs:
            outputs = outputs + inputs
        else:
            outputs = [out + inp for out, inp in zip(outputs, inputs)]
        return outputs, states


class BidirectionalCell(HybridRecurrentCell):
    """Run two cells over the sequence in opposite directions and concat
    their outputs (parity: rnn_cell.py:1018).  Only usable via ``unroll``."""

    def __init__(self, l_cell, r_cell, output_prefix='bi_'):
        super().__init__(prefix='', params=None)
        self.register_child(l_cell, 'l_cell')
        self.register_child(r_cell, 'r_cell')
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def __repr__(self):
        return '%s(forward=%r, backward=%r)' % (
            self.__class__.__name__, self._children['l_cell'],
            self._children['r_cell'])

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(),
                                  batch_size=batch_size, func=func, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None, valid_length=None):
        self.reset()
        F, inputs, axis, batch_size = _format_sequence(length, inputs,
                                                       layout, False)
        reversed_inputs = list(_reverse_sequences(inputs, length,
                                                  valid_length))
        begin_state = _get_begin_state(self, F, begin_state, inputs,
                                       batch_size)

        states = begin_state
        l_cell, r_cell = self._children.values()
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=states[:len(l_cell.state_info())],
            layout=layout, merge_outputs=merge_outputs,
            valid_length=valid_length)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=reversed_inputs,
            begin_state=states[len(l_cell.state_info()):],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        reversed_r_outputs = _reverse_sequences(r_outputs, length,
                                                valid_length)

        if merge_outputs is None:
            merge_outputs = isinstance(l_outputs, nd_mod.NDArray)
            _, l_outputs, _, _ = _format_sequence(None, l_outputs, layout,
                                                  merge_outputs)
        _, reversed_r_outputs, _, _ = _format_sequence(
            None, reversed_r_outputs, layout, merge_outputs)

        if merge_outputs:
            reversed_r_outputs = F.stack(*reversed_r_outputs, axis=axis) \
                if isinstance(reversed_r_outputs, list) else \
                reversed_r_outputs
            outputs = F.concat(l_outputs, reversed_r_outputs, dim=2)
        else:
            outputs = [F.concat(l_o, r_o, dim=1)
                       for l_o, r_o in zip(l_outputs, reversed_r_outputs)]
        if valid_length is not None:
            outputs = _mask_sequence_variable_length(
                F, outputs, length, valid_length, axis, merge_outputs)
        states = l_states + r_states
        return outputs, states
