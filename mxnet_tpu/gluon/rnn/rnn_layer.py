"""Fused recurrent layers (parity: ``python/mxnet/gluon/rnn/rnn_layer.py``).

``RNN``/``LSTM``/``GRU`` keep per-layer/direction ``{l,r}{i}_{i2h,h2h}_
{weight,bias}`` parameters exactly like the reference (rnn_layer.py:34) but
run the whole multi-layer recurrence through the monolithic ``RNN`` op
(ops/nn.py, parity rnn.cc:299) whose time loop is a ``lax.scan`` — one XLA
executable regardless of sequence length, per-step matmuls on the MXU.
"""
from __future__ import annotations

from ... import ndarray as nd_mod
from ...base import MXNetError
from ...ops.nn import _gates
from ..block import HybridBlock

__all__ = ['RNN', 'LSTM', 'GRU']


class _RNNLayer(HybridBlock):
    """Base fused layer (parity: rnn_layer.py:34)."""

    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, projection_size=None,
                 prefix=None, params=None):
        self._mode = mode  # before super().__init__: _alias() needs it
        super().__init__(prefix=prefix, params=params)
        assert layout in ('TNC', 'NTC'), \
            "Invalid layout %s; must be one of ['TNC', 'NTC']" % layout
        if projection_size:
            raise MXNetError("projection_size (LSTMP) is not supported")
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = _gates(mode)

        ng, ni, nh = self._gates, input_size, hidden_size
        with self.name_scope():
            for i in range(num_layers):
                for j in ['l', 'r'][:self._dir]:
                    for name, shape, init in [
                            ('i2h_weight', (ng * nh, ni),
                             i2h_weight_initializer),
                            ('h2h_weight', (ng * nh, nh),
                             h2h_weight_initializer),
                            ('i2h_bias', (ng * nh,), i2h_bias_initializer),
                            ('h2h_bias', (ng * nh,), h2h_bias_initializer)]:
                        pname = '%s%d_%s' % (j, i, name)
                        setattr(self, pname, self.params.get(
                            pname, shape=shape, init=init,
                            allow_deferred_init=True))
                ni = nh * self._dir

    def __repr__(self):
        s = '{name}({mapping}, {_layout}'
        if self._num_layers != 1:
            s += ', num_layers={_num_layers}'
        if self._dropout != 0:
            s += ', dropout={_dropout}'
        if self._dir == 2:
            s += ', bidirectional'
        s += ')'
        shape = self.l0_i2h_weight.shape
        mapping = '%s -> %s' % (shape[1] if shape[1] else None,
                                shape[0] // self._gates)
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)

    def _alias(self):
        return self._mode

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial recurrent states for a batch (zeros by default)."""
        if func is None:
            func = nd_mod.zeros
        states = []
        for info in self.state_info(batch_size):
            info = dict(info)
            info.update(kwargs)
            shape = info.pop('shape')
            info.pop('__layout__', None)
            states.append(func(shape, **info))
        return states

    def _ordered_param_names(self):
        """Registered param names in the packed-vector layout the RNN op
        expects (ops/nn.py _unpack_rnn_params; reference rnn-inl.h): all
        weights per layer/direction (W_x then W_h), then all biases."""
        weights, biases = [], []
        for i in range(self._num_layers):
            for j in ['l', 'r'][:self._dir]:
                weights.append('%s%d_i2h_weight' % (j, i))
                weights.append('%s%d_h2h_weight' % (j, i))
                biases.append('%s%d_i2h_bias' % (j, i))
                biases.append('%s%d_h2h_bias' % (j, i))
        return weights + biases

    def _shape_hint(self, inputs, *states):
        if self.l0_i2h_weight.shape and self.l0_i2h_weight.shape[1] == 0:
            ni = inputs.shape[2]
            for j in ['l', 'r'][:self._dir]:
                p = getattr(self, '%s0_i2h_weight' % j)
                p.shape = (self._gates * self._hidden_size, ni)

    def forward(self, inputs, states=None):
        """Run the fused recurrence.

        Returns ``output`` if ``states`` is None, else
        ``(output, new_states)`` — matching the reference (_RNNLayer
        .forward semantics, rnn_layer.py:244).
        """
        skip_states = states is None
        if not skip_states:
            if isinstance(states, nd_mod.NDArray):
                states = [states]
            out = super().forward(inputs, *states)
        else:
            out = super().forward(inputs)
        if skip_states:
            return out[0]
        return out[0], list(out[1:])

    def hybrid_forward(self, F, inputs, *states, **params):
        if self._layout == 'NTC':
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        batch_size = inputs.shape[1]
        states = list(states)
        if not states:
            states = self.begin_state(
                batch_size, func=nd_mod.zeros, dtype=inputs.dtype,
                ctx=getattr(inputs, 'context', None))
        flat = [params[name] for name in self._ordered_param_names()]
        param_vec = F.concat(*[w.reshape((-1,)) for w in flat], dim=0)
        rnn_args = [inputs, param_vec, states[0]]
        if self._mode == 'lstm':
            rnn_args.append(states[1])
        out = F.RNN(*rnn_args, state_size=self._hidden_size,
                    num_layers=self._num_layers,
                    bidirectional=self._dir == 2, mode=self._mode,
                    p=self._dropout, state_outputs=True)
        outputs, state_h, state_c = out[0], out[1], out[2]
        if self._layout == 'NTC':
            outputs = F.swapaxes(outputs, dim1=0, dim2=1)
        if self._mode == 'lstm':
            return outputs, state_h, state_c
        return outputs, state_h

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None, valid_length=None):
        """Cell-style unroll API on the fused layer (convenience)."""
        from .rnn_cell import _format_sequence
        F, inputs, axis, batch_size = _format_sequence(length, inputs,
                                                       layout, True)
        states = begin_state or self.begin_state(batch_size, func=F.zeros)
        outputs, states = self.forward(
            inputs if layout == self._layout
            else F.swapaxes(inputs, dim1=0, dim2=1), states)
        if layout != self._layout:
            outputs = F.swapaxes(outputs, dim1=0, dim2=1)
        if valid_length is not None:
            outputs = F.SequenceMask(outputs, sequence_length=valid_length,
                                     use_sequence_length=True, axis=axis)
        if merge_outputs is False:
            outputs = F.split(outputs, num_outputs=length, axis=axis,
                              squeeze_axis=True)
        return outputs, states


class RNN(_RNNLayer):
    """Multi-layer Elman RNN with tanh/relu nonlinearity
    (parity: rnn_layer.py:307)."""

    def __init__(self, hidden_size, num_layers=1, activation='relu',
                 layout='TNC', dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer='zeros', h2h_bias_initializer='zeros',
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         'rnn_' + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{'shape': (self._num_layers * self._dir, batch_size,
                           self._hidden_size), '__layout__': 'LNC'}]


class LSTM(_RNNLayer):
    """Multi-layer LSTM (parity: rnn_layer.py:404)."""

    def __init__(self, hidden_size, num_layers=1, layout='TNC', dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer='zeros', h2h_bias_initializer='zeros',
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         'lstm', **kwargs)

    def state_info(self, batch_size=0):
        return [{'shape': (self._num_layers * self._dir, batch_size,
                           self._hidden_size), '__layout__': 'LNC'},
                {'shape': (self._num_layers * self._dir, batch_size,
                           self._hidden_size), '__layout__': 'LNC'}]


class GRU(_RNNLayer):
    """Multi-layer GRU (cuDNN variant: reset gate applied to h2h output)
    (parity: rnn_layer.py:535)."""

    def __init__(self, hidden_size, num_layers=1, layout='TNC', dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer='zeros', h2h_bias_initializer='zeros',
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         'gru', **kwargs)

    def state_info(self, batch_size=0):
        return [{'shape': (self._num_layers * self._dir, batch_size,
                           self._hidden_size), '__layout__': 'LNC'}]
