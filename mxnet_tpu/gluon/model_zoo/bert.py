"""Transformer encoder / BERT model family.

The reference era shipped transformer building blocks as fused CUDA ops
(``src/operator/contrib/transformer.cc``) and left BERT to gluon-nlp;
the rebuild provides the full model family natively, TPU-first:
attention runs through the Pallas flash-attention op
(``_contrib_flash_attention`` — blockwise online softmax on the MXU),
QKV is ONE fused projection (the interleaved_matmul layout), and
everything is a HybridBlock so the whole encoder lowers to a single XLA
executable under ``hybridize()``/``JitTrainStep``.

Long sequences: combine with ``parallel.ring_attention_sharded`` to
shard T across chips (SURVEY §5.7 long-context design).
"""
from __future__ import annotations

import math

from .. import nn
from ..block import HybridBlock


class MultiHeadAttention(HybridBlock):
    """Self-attention with fused QKV and flash-attention scores."""

    def __init__(self, units, num_heads, dropout=0.0, causal=False,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if units % num_heads:
            raise ValueError("units must divide num_heads")
        self._units = units
        self._heads = num_heads
        self._causal = causal
        with self.name_scope():
            self.qkv = nn.Dense(3 * units, flatten=False, use_bias=True,
                                prefix="qkv_")
            self.proj = nn.Dense(units, flatten=False, use_bias=True,
                                 prefix="proj_")
            self.drop = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        b, t, _ = x.shape
        h, d = self._heads, self._units // self._heads
        qkv = self.qkv(x)                                   # (B,T,3C)
        qkv = F.reshape(qkv, shape=(b, t, 3, h, d))
        qkv = F.transpose(qkv, axes=(2, 0, 3, 1, 4))        # (3,B,H,T,D)
        q = F.squeeze(F.slice_axis(qkv, axis=0, begin=0, end=1), axis=0)
        k = F.squeeze(F.slice_axis(qkv, axis=0, begin=1, end=2), axis=0)
        v = F.squeeze(F.slice_axis(qkv, axis=0, begin=2, end=3), axis=0)
        out = F.contrib.flash_attention(
            q, k, v, scale=1.0 / math.sqrt(d), causal=self._causal)
        out = F.reshape(F.transpose(out, axes=(0, 2, 1, 3)),
                        shape=(b, t, self._units))
        out = self.proj(out)
        if self.drop is not None:
            out = self.drop(out)
        return out


class PositionwiseFFN(HybridBlock):
    """Two-layer MLP with GELU (BERT's FFN)."""

    def __init__(self, units, hidden_size, dropout=0.0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.fc1 = nn.Dense(hidden_size, flatten=False, prefix="fc1_")
            self.fc2 = nn.Dense(units, flatten=False, prefix="fc2_")
            self.drop = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        y = self.fc1(x)
        y = 0.5 * y * (1.0 + F.erf(y / math.sqrt(2.0)))  # exact GELU
        y = self.fc2(y)
        if self.drop is not None:
            y = self.drop(y)
        return y


class TransformerEncoderCell(HybridBlock):
    """Post-LN encoder layer (BERT convention)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 causal=False, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.attn = MultiHeadAttention(units, num_heads, dropout,
                                           causal, prefix="attn_")
            self.ln1 = nn.LayerNorm(prefix="ln1_")
            self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                       prefix="ffn_")
            self.ln2 = nn.LayerNorm(prefix="ln2_")

    def hybrid_forward(self, F, x):
        x = self.ln1(x + self.attn(x))
        x = self.ln2(x + self.ffn(x))
        return x


class BERTEncoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads,
                 dropout=0.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.layers = nn.HybridSequential(prefix="layers_")
            for i in range(num_layers):
                self.layers.add(TransformerEncoderCell(
                    units, hidden_size, num_heads, dropout,
                    prefix="cell%d_" % i))

    def hybrid_forward(self, F, x):
        return self.layers(x)


class BERTModel(HybridBlock):
    """BERT-style masked-LM encoder.

    forward(tokens, token_types) → (sequence_output (B,T,C),
    pooled_output (B,C) from the CLS position, mlm_logits (B,T,V));
    the MLM decoder ties the word embedding.
    """

    def __init__(self, vocab_size, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512,
                 type_vocab=2, dropout=0.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, units,
                                           prefix="word_")
            self.type_embed = nn.Embedding(type_vocab, units,
                                           prefix="type_")
            self.pos_embed = self.params.get(
                "pos_embed", shape=(max_length, units),
                init=None, allow_deferred_init=False)
            self.ln = nn.LayerNorm(prefix="embln_")
            self.drop = nn.Dropout(dropout) if dropout else None
            self.encoder = BERTEncoder(num_layers, units, hidden_size,
                                       num_heads, dropout,
                                       prefix="enc_")
            self.pooler = nn.Dense(units, flatten=False,
                                   activation="tanh", prefix="pooler_")
            self.mlm_bias = self.params.get(
                "mlm_bias", shape=(vocab_size,), init="zeros",
                allow_deferred_init=False)

    def hybrid_forward(self, F, tokens, token_types=None, pos_embed=None,
                       mlm_bias=None):
        b, t = tokens.shape
        emb = self.word_embed(tokens)
        if token_types is not None:
            emb = emb + self.type_embed(token_types)
        pos = F.slice_axis(pos_embed, axis=0, begin=0, end=t)
        emb = emb + F.expand_dims(pos, axis=0)
        emb = self.ln(emb)
        if self.drop is not None:
            emb = self.drop(emb)
        seq = self.encoder(emb)
        pooled = self.pooler(F.squeeze(
            F.slice_axis(seq, axis=1, begin=0, end=1), axis=1))
        # tied MLM head: logits = seq · E^T + b
        w = self.word_embed.weight.data()
        logits = F.dot(F.reshape(seq, shape=(b * t, self._units)), w,
                       transpose_b=True)
        logits = F.reshape(logits, shape=(b, t, -1)) + mlm_bias
        return seq, pooled, logits


def bert_base(vocab_size=30522, **kwargs):
    """BERT-base (110M params): 12 layers, 768 units, 12 heads."""
    cfg = dict(units=768, hidden_size=3072, num_layers=12, num_heads=12)
    cfg.update(kwargs)
    return BERTModel(vocab_size, **cfg)


def bert_small(vocab_size=1000, **kwargs):
    """Tiny config for tests / dry-runs."""
    cfg = dict(units=64, hidden_size=128, num_layers=2, num_heads=4,
               max_length=128)
    cfg.update(kwargs)
    return BERTModel(vocab_size, **cfg)
