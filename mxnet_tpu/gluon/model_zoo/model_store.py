"""Pretrained-weight store (parity: gluon/model_zoo/model_store.py).

``get_model_file(name)`` returns the local path of a model's ``.params``
checkpoint, downloading it from the Gluon repository when absent.  The
repository base is ``MXNET_GLUON_REPO`` (see ``gluon.utils._get_repo_url``)
— point it at a ``file://`` tree or internal mirror in air-gapped
deployments; no sha1 table is baked in (the reference pins known-model
hashes; here any repo-served checkpoint for the NAMED model is accepted,
with sha1 verification when the repo publishes ``<file>.sha1``).
"""
import os

from ..utils import _get_repo_file_url, check_sha1, download

_NAMESPACE = "gluon/models"


def get_model_file(name, root=os.path.join("~", ".mxnet", "models")):
    """Local path of ``<name>.params``, downloaded on first use."""
    file_name = "%s.params" % name
    root = os.path.expanduser(root)
    path = os.path.join(root, file_name)
    if os.path.exists(path):
        return path
    if "MXNET_GLUON_REPO" not in os.environ:
        # the default public bucket stores hash-suffixed archives this
        # rebuild does not mirror; hammering it would 404 through every
        # retry.  Be direct about what works instead.
        raise FileNotFoundError(
            "%s not found locally (%s) and no MXNET_GLUON_REPO is set. "
            "Place the checkpoint there, or point MXNET_GLUON_REPO at a "
            "repository (https:// or file://) serving "
            "gluon/models/%s" % (file_name, path, file_name))
    os.makedirs(root, exist_ok=True)
    url = _get_repo_file_url(_NAMESPACE, file_name)
    sha1 = None
    try:  # optional integrity sidecar published next to the checkpoint
        sha_path = download(url + ".sha1", path=path + ".sha1",
                            overwrite=True, retries=0)
        sha1 = open(sha_path).read().split()[0].strip() or None
    except Exception:
        sha1 = None
    try:
        download(url, path=path, sha1_hash=sha1)
        if sha1 and not check_sha1(path, sha1):
            raise ValueError(
                "downloaded %s does not match its published sha1"
                % file_name)
    finally:
        try:
            os.remove(path + ".sha1")
        except OSError:
            pass
    return path


def purge(root=os.path.join("~", ".mxnet", "models")):
    """Remove all cached model files (reference model_store.purge)."""
    root = os.path.expanduser(root)
    if not os.path.isdir(root):
        return
    for f in os.listdir(root):
        if f.endswith((".params", ".params.sha1")):
            os.remove(os.path.join(root, f))
