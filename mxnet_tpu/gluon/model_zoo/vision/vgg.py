"""VGG 11/13/16/19 ±BN (parity: gluon/model_zoo/vision/vgg.py)."""
from __future__ import annotations

import os

from ... import nn
from ....context import cpu
from .... import initializer as init
from ._base import _LayoutNet


class VGG(_LayoutNet):
    def __init__(self, layers, filters, classes=1000, batch_norm=False,
                 layout=None, **kwargs):
        super().__init__(layout=layout, **kwargs)
        assert len(layers) == len(filters)
        with self._build_scope(), self.name_scope():
            self.features = self._make_features(layers, filters,
                                                batch_norm)
            self.features.add(nn.Dense(
                4096, activation='relu',
                weight_initializer='normal'))
            self.features.add(nn.Dropout(rate=0.5))
            self.features.add(nn.Dense(
                4096, activation='relu',
                weight_initializer='normal'))
            self.features.add(nn.Dropout(rate=0.5))
            self.output = nn.Dense(classes, weight_initializer='normal')

    def _make_features(self, layers, filters, batch_norm):
        featurizer = nn.HybridSequential(prefix='')
        for i, num in enumerate(layers):
            for _ in range(num):
                featurizer.add(nn.Conv2D(
                    filters[i], kernel_size=3, padding=1,
                    weight_initializer=init.Xavier(
                        rnd_type='gaussian', factor_type='out',
                        magnitude=2)))
                if batch_norm:
                    featurizer.add(nn.BatchNorm())
                featurizer.add(nn.Activation('relu'))
            featurizer.add(nn.MaxPool2D(strides=2))
        return featurizer

    def hybrid_forward(self, F, x):
        x = self._stem_input(F, x)
        x = self.features(x)
        return self.output(x)


vgg_spec = {
    11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
    13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
    16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
    19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512]),
}


def get_vgg(num_layers, pretrained=False, ctx=cpu(),
            root=os.path.join('~', '.mxnet', 'models'), **kwargs):
    layers, filters = vgg_spec[num_layers]
    if pretrained:
        # shipped checkpoints are reference-layout (NCHW/OIHW)
        kwargs.setdefault('layout', 'NCHW')
    net = VGG(layers, filters, **kwargs)
    if pretrained:
        batch_norm_suffix = '_bn' if kwargs.get('batch_norm') else ''
        net.load_parameters(os.path.join(
            os.path.expanduser(root),
            'vgg%d%s.params' % (num_layers, batch_norm_suffix)), ctx=ctx)
    return net


def vgg11(**kwargs):
    return get_vgg(11, **kwargs)


def vgg13(**kwargs):
    return get_vgg(13, **kwargs)


def vgg16(**kwargs):
    return get_vgg(16, **kwargs)


def vgg19(**kwargs):
    return get_vgg(19, **kwargs)


def vgg11_bn(**kwargs):
    kwargs['batch_norm'] = True
    return get_vgg(11, **kwargs)


def vgg13_bn(**kwargs):
    kwargs['batch_norm'] = True
    return get_vgg(13, **kwargs)


def vgg16_bn(**kwargs):
    kwargs['batch_norm'] = True
    return get_vgg(16, **kwargs)


def vgg19_bn(**kwargs):
    kwargs['batch_norm'] = True
    return get_vgg(19, **kwargs)
