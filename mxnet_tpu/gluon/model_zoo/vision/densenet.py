"""DenseNet 121/161/169/201 (parity: gluon/model_zoo/vision/densenet.py)."""
from __future__ import annotations

import os

from ...block import HybridBlock
from ... import nn
from .... import layout as layout_mod
from ....context import cpu
from ._base import _LayoutNet


def _make_dense_block(num_layers, bn_size, growth_rate, dropout,
                      stage_index):
    out = nn.HybridSequential(prefix='stage%d_' % stage_index)
    with out.name_scope():
        for _ in range(num_layers):
            out.add(_DenseLayer(growth_rate, bn_size, dropout))
    return out


class _DenseLayer(HybridBlock):
    """BN-ReLU-1x1 - BN-ReLU-3x3, output concat with input."""

    def __init__(self, growth_rate, bn_size, dropout, **kwargs):
        super().__init__(**kwargs)
        self._caxis = layout_mod.current_channel_axis()
        self.body = nn.HybridSequential(prefix='')
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation('relu'))
        self.body.add(nn.Conv2D(bn_size * growth_rate, kernel_size=1,
                                use_bias=False))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation('relu'))
        self.body.add(nn.Conv2D(growth_rate, kernel_size=3, padding=1,
                                use_bias=False))
        if dropout:
            self.body.add(nn.Dropout(dropout))

    def hybrid_forward(self, F, x):
        out = self.body(x)
        return F.concat(x, out, dim=self._caxis)


def _make_transition(num_output_features):
    out = nn.HybridSequential(prefix='')
    out.add(nn.BatchNorm())
    out.add(nn.Activation('relu'))
    out.add(nn.Conv2D(num_output_features, kernel_size=1, use_bias=False))
    out.add(nn.AvgPool2D(pool_size=2, strides=2))
    return out


class DenseNet(_LayoutNet):
    def __init__(self, num_init_features, growth_rate, block_config,
                 bn_size=4, dropout=0, classes=1000, layout=None, **kwargs):
        super().__init__(layout=layout, **kwargs)
        with self._build_scope(), self.name_scope():
            self.features = nn.HybridSequential(prefix='')
            self.features.add(nn.Conv2D(
                num_init_features, kernel_size=7, strides=2, padding=3,
                use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation('relu'))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           padding=1))
            num_features = num_init_features
            for i, num_layers in enumerate(block_config):
                self.features.add(_make_dense_block(
                    num_layers, bn_size, growth_rate, dropout, i + 1))
                num_features = num_features + num_layers * growth_rate
                if i != len(block_config) - 1:
                    num_features = num_features // 2
                    self.features.add(_make_transition(num_features))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation('relu'))
            self.features.add(nn.AvgPool2D(pool_size=7))
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self._stem_input(F, x)
        x = self.features(x)
        return self.output(x)


# num_init_features, growth_rate, block_config
densenet_spec = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
}


def get_densenet(num_layers, pretrained=False, ctx=cpu(),
                 root=os.path.join('~', '.mxnet', 'models'), **kwargs):
    num_init_features, growth_rate, block_config = densenet_spec[num_layers]
    if pretrained:
        # shipped checkpoints are reference-layout (NCHW/OIHW)
        kwargs.setdefault('layout', 'NCHW')
    net = DenseNet(num_init_features, growth_rate, block_config, **kwargs)
    if pretrained:
        net.load_parameters(os.path.join(
            os.path.expanduser(root),
            'densenet%d.params' % num_layers), ctx=ctx)
    return net


def densenet121(**kwargs):
    return get_densenet(121, **kwargs)


def densenet161(**kwargs):
    return get_densenet(161, **kwargs)


def densenet169(**kwargs):
    return get_densenet(169, **kwargs)


def densenet201(**kwargs):
    return get_densenet(201, **kwargs)
