"""Shared base for model-zoo vision networks: layout parametrisation.

Reference model-zoo nets (``python/mxnet/gluon/model_zoo/vision/``) are
NCHW-only.  Here every family is layout-parametric so the whole graph can
run channels-last on the MXU (see ``mxnet_tpu/layout.py``), while the
user-facing contract stays reference-compatible: nets accept NCHW image
batches and transpose once at the stem.
"""
from __future__ import annotations

from ...block import HybridBlock
from .... import layout as layout_mod


class _LayoutNet(HybridBlock):
    """Base for model-zoo vision nets: layout-parametric, NCHW boundary.

    ``layout=None`` resolves through the global policy (``layout.py``) —
    channels-last on TPU.  The net always ACCEPTS NCHW image batches (API
    parity with the reference model zoo); when the internal layout is
    channels-last the input is transposed once at the stem, which XLA folds
    into the first convolution's relayout.
    """

    def __init__(self, layout=None, **kwargs):
        super().__init__(**kwargs)
        self._layout = layout if layout is not None \
            else layout_mod.preferred_layout(2)

    def _build_scope(self):
        """Context manager: build child layers under this net's layout."""
        return layout_mod.layout_scope(self._layout)

    def _stem_input(self, F, x):
        if not self._layout.startswith("NC"):
            return F.transpose(x, axes=(0, 2, 3, 1))
        return x
