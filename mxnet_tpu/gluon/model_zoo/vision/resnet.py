"""ResNet v1/v2 (parity: gluon/model_zoo/vision/resnet.py).

The flagship benchmark model: on TPU, the whole network hybridizes to one
XLA program — conv+BN+relu fuse on the MXU/VPU, so the definition stays
pure and high-level.
"""
from __future__ import annotations

import os

from ...block import HybridBlock
from ... import nn
from ....context import cpu
from ._base import _LayoutNet


def _conv3x3(channels, stride, in_channels):
    return nn.Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                     use_bias=False, in_channels=in_channels)


class BasicBlockV1(HybridBlock):
    """ResNet-v1 basic block: conv-bn-relu x2 + residual."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix='')
        self.body.add(_conv3x3(channels, stride, in_channels))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation('relu'))
        self.body.add(_conv3x3(channels, 1, channels))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential(prefix='')
            self.downsample.add(nn.Conv2D(
                channels, kernel_size=1, strides=stride, use_bias=False,
                in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.relu(residual + x)


class BottleneckV1(HybridBlock):
    """ResNet-v1 bottleneck: 1x1 - 3x3 - 1x1 + residual."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix='')
        self.body.add(nn.Conv2D(channels // 4, kernel_size=1,
                                strides=stride))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation('relu'))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation('relu'))
        self.body.add(nn.Conv2D(channels, kernel_size=1, strides=1))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential(prefix='')
            self.downsample.add(nn.Conv2D(
                channels, kernel_size=1, strides=stride, use_bias=False,
                in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.relu(x + residual)


class BasicBlockV2(HybridBlock):
    """ResNet-v2 basic block: pre-activation."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = _conv3x3(channels, stride, in_channels)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(channels, 1, channels)
        if downsample:
            self.downsample = nn.Conv2D(
                channels, 1, stride, use_bias=False,
                in_channels=in_channels)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.relu(x)
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.relu(x)
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    """ResNet-v2 bottleneck: pre-activation."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = nn.Conv2D(channels // 4, kernel_size=1, strides=1,
                               use_bias=False)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4)
        self.bn3 = nn.BatchNorm()
        self.conv3 = nn.Conv2D(channels, kernel_size=1, strides=1,
                               use_bias=False)
        if downsample:
            self.downsample = nn.Conv2D(
                channels, 1, stride, use_bias=False,
                in_channels=in_channels)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.relu(x)
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.relu(x)
        x = self.conv2(x)
        x = self.bn3(x)
        x = F.relu(x)
        x = self.conv3(x)
        return x + residual


class ResNetV1(_LayoutNet):
    """ResNet v1 (parity: resnet.py ResNetV1)."""

    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, layout=None, **kwargs):
        super().__init__(layout=layout, **kwargs)
        assert len(layers) == len(channels) - 1
        with self._build_scope(), self.name_scope():
            self.features = nn.HybridSequential(prefix='')
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0))
            else:
                self.features.add(nn.Conv2D(
                    channels[0], 7, 2, 3, use_bias=False))
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation('relu'))
                self.features.add(nn.MaxPool2D(3, 2, 1))
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=channels[i]))
            self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, stage_index,
                    in_channels=0):
        layer = nn.HybridSequential(prefix='stage%d_' % stage_index)
        with layer.name_scope():
            layer.add(block(channels, stride, channels != in_channels,
                            in_channels=in_channels, prefix=''))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False, in_channels=channels,
                                prefix=''))
        return layer

    def hybrid_forward(self, F, x):
        x = self._stem_input(F, x)
        x = self.features(x)
        return self.output(x)


class ResNetV2(_LayoutNet):
    """ResNet v2 (parity: resnet.py ResNetV2)."""

    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, layout=None, **kwargs):
        super().__init__(layout=layout, **kwargs)
        assert len(layers) == len(channels) - 1
        with self._build_scope(), self.name_scope():
            self.features = nn.HybridSequential(prefix='')
            self.features.add(nn.BatchNorm(scale=False, center=False))
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0))
            else:
                self.features.add(nn.Conv2D(
                    channels[0], 7, 2, 3, use_bias=False))
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation('relu'))
                self.features.add(nn.MaxPool2D(3, 2, 1))
            in_channels = channels[0]
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=in_channels))
                in_channels = channels[i + 1]
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation('relu'))
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes, in_units=in_channels)

    def _make_layer(self, block, layers, channels, stride, stage_index,
                    in_channels=0):
        layer = nn.HybridSequential(prefix='stage%d_' % stage_index)
        with layer.name_scope():
            layer.add(block(channels, stride, channels != in_channels,
                            in_channels=in_channels, prefix=''))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False, in_channels=channels,
                                prefix=''))
        return layer

    def hybrid_forward(self, F, x):
        x = self._stem_input(F, x)
        x = self.features(x)
        return self.output(x)


resnet_spec = {
    18: ('basic_block', [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ('basic_block', [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ('bottle_neck', [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ('bottle_neck', [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ('bottle_neck', [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}
resnet_net_versions = [ResNetV1, ResNetV2]
resnet_block_versions = [
    {'basic_block': BasicBlockV1, 'bottle_neck': BottleneckV1},
    {'basic_block': BasicBlockV2, 'bottle_neck': BottleneckV2},
]


def get_resnet(version, num_layers, pretrained=False, ctx=cpu(),
               root=os.path.join('~', '.mxnet', 'models'), **kwargs):
    assert num_layers in resnet_spec, \
        "Invalid number of layers: %d. Options are %s" % (
            num_layers, str(resnet_spec.keys()))
    block_type, layers, channels = resnet_spec[num_layers]
    assert 1 <= version <= 2, \
        "Invalid resnet version: %d. Options are 1 and 2." % version
    resnet_class = resnet_net_versions[version - 1]
    block_class = resnet_block_versions[version - 1][block_type]
    if pretrained:
        # shipped checkpoints are reference-layout (NCHW/OIHW)
        kwargs.setdefault('layout', 'NCHW')
    net = resnet_class(block_class, layers, channels, **kwargs)
    if pretrained:
        # local file wins; otherwise fetched from the model store
        # (MXNET_GLUON_REPO — file:// trees work for air-gapped use)
        from ..model_store import get_model_file

        net.load_parameters(
            get_model_file('resnet%d_v%d' % (num_layers, version),
                           root=root), ctx=ctx)
    return net


def resnet18_v1(**kwargs):
    return get_resnet(1, 18, **kwargs)


def resnet34_v1(**kwargs):
    return get_resnet(1, 34, **kwargs)


def resnet50_v1(**kwargs):
    return get_resnet(1, 50, **kwargs)


def resnet101_v1(**kwargs):
    return get_resnet(1, 101, **kwargs)


def resnet152_v1(**kwargs):
    return get_resnet(1, 152, **kwargs)


def resnet18_v2(**kwargs):
    return get_resnet(2, 18, **kwargs)


def resnet34_v2(**kwargs):
    return get_resnet(2, 34, **kwargs)


def resnet50_v2(**kwargs):
    return get_resnet(2, 50, **kwargs)


def resnet101_v2(**kwargs):
    return get_resnet(2, 101, **kwargs)


def resnet152_v2(**kwargs):
    return get_resnet(2, 152, **kwargs)
