"""AlexNet (parity: gluon/model_zoo/vision/alexnet.py)."""
from __future__ import annotations

import os

from ... import nn
from ....context import cpu
from ._base import _LayoutNet


class AlexNet(_LayoutNet):
    def __init__(self, classes=1000, layout=None, **kwargs):
        super().__init__(layout=layout, **kwargs)
        with self._build_scope(), self.name_scope():
            self.features = nn.HybridSequential(prefix='')
            with self.features.name_scope():
                self.features.add(nn.Conv2D(
                    64, kernel_size=11, strides=4, padding=2,
                    activation='relu'))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
                self.features.add(nn.Conv2D(
                    192, kernel_size=5, padding=2, activation='relu'))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
                self.features.add(nn.Conv2D(
                    384, kernel_size=3, padding=1, activation='relu'))
                self.features.add(nn.Conv2D(
                    256, kernel_size=3, padding=1, activation='relu'))
                self.features.add(nn.Conv2D(
                    256, kernel_size=3, padding=1, activation='relu'))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
                self.features.add(nn.Flatten())
                self.features.add(nn.Dense(4096, activation='relu'))
                self.features.add(nn.Dropout(0.5))
                self.features.add(nn.Dense(4096, activation='relu'))
                self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self._stem_input(F, x)
        x = self.features(x)
        return self.output(x)


def alexnet(pretrained=False, ctx=cpu(),
            root=os.path.join('~', '.mxnet', 'models'), **kwargs):
    if pretrained:
        # shipped checkpoints are reference-layout (NCHW/OIHW)
        kwargs.setdefault('layout', 'NCHW')
    net = AlexNet(**kwargs)
    if pretrained:
        net.load_parameters(
            os.path.join(os.path.expanduser(root), 'alexnet.params'),
            ctx=ctx)
    return net
