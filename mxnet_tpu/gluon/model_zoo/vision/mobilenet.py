"""MobileNet v1/v2 (parity: gluon/model_zoo/vision/mobilenet.py).

Depthwise convs map to XLA ``feature_group_count`` grouped convolutions —
the layers pass ``groups=channels``.
"""
from __future__ import annotations

import os

from ...block import HybridBlock
from ... import nn
from ....context import cpu
from ._base import _LayoutNet


class RELU6(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.clip(x, 0, 6)


def _add_conv(out, channels=1, kernel=1, stride=1, pad=0, num_group=1,
              active=True, relu6=False):
    out.add(nn.Conv2D(channels, kernel, stride, pad, groups=num_group,
                      use_bias=False))
    out.add(nn.BatchNorm())
    if active:
        out.add(RELU6() if relu6 else nn.Activation('relu'))


def _add_conv_dw(out, dw_channels, channels, stride, relu6=False):
    _add_conv(out, dw_channels, kernel=3, stride=stride, pad=1,
              num_group=dw_channels, relu6=relu6)
    _add_conv(out, channels, relu6=relu6)


class LinearBottleneck(HybridBlock):
    """MobileNetV2 inverted residual (parity: mobilenet.py:80)."""

    def __init__(self, in_channels, channels, t, stride, **kwargs):
        super().__init__(**kwargs)
        self.use_shortcut = stride == 1 and in_channels == channels
        with self.name_scope():
            self.out = nn.HybridSequential()
            _add_conv(self.out, in_channels * t, relu6=True)
            _add_conv(self.out, in_channels * t, kernel=3, stride=stride,
                      pad=1, num_group=in_channels * t, relu6=True)
            _add_conv(self.out, channels, active=False, relu6=True)

    def hybrid_forward(self, F, x):
        out = self.out(x)
        if self.use_shortcut:
            out = F.elemwise_add(out, x)
        return out


class MobileNet(_LayoutNet):
    """MobileNet v1 (parity: mobilenet.py MobileNet:107)."""

    def __init__(self, multiplier=1.0, classes=1000, layout=None, **kwargs):
        super().__init__(layout=layout, **kwargs)
        with self._build_scope(), self.name_scope():
            self.features = nn.HybridSequential(prefix='')
            with self.features.name_scope():
                _add_conv(self.features, channels=int(32 * multiplier),
                          kernel=3, pad=1, stride=2)
                dw_channels = [int(x * multiplier) for x in
                               [32, 64] + [128] * 2 + [256] * 2 +
                               [512] * 6 + [1024]]
                channels = [int(x * multiplier) for x in
                            [64] + [128] * 2 + [256] * 2 + [512] * 6 +
                            [1024] * 2]
                strides = [1, 2] * 3 + [1] * 5 + [2, 1]
                for dwc, c, s in zip(dw_channels, channels, strides):
                    _add_conv_dw(self.features, dw_channels=dwc,
                                 channels=c, stride=s)
                self.features.add(nn.GlobalAvgPool2D())
                self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self._stem_input(F, x)
        x = self.features(x)
        return self.output(x)


class MobileNetV2(_LayoutNet):
    """MobileNet v2 (parity: mobilenet.py MobileNetV2:160)."""

    def __init__(self, multiplier=1.0, classes=1000, layout=None, **kwargs):
        super().__init__(layout=layout, **kwargs)
        with self._build_scope(), self.name_scope():
            self.features = nn.HybridSequential(prefix='features_')
            with self.features.name_scope():
                _add_conv(self.features, int(32 * multiplier), kernel=3,
                          stride=2, pad=1, relu6=True)
                in_channels_group = [int(x * multiplier) for x in
                                     [32] + [16] + [24] * 2 + [32] * 3 +
                                     [64] * 4 + [96] * 3 + [160] * 3]
                channels_group = [int(x * multiplier) for x in
                                  [16] + [24] * 2 + [32] * 3 + [64] * 4 +
                                  [96] * 3 + [160] * 3 + [320]]
                ts = [1] + [6] * 16
                strides = [1, 2] * 2 + [1, 1, 2] + [1] * 6 + [2] + [1] * 3
                for in_c, c, t, s in zip(in_channels_group, channels_group,
                                         ts, strides):
                    self.features.add(LinearBottleneck(
                        in_channels=in_c, channels=c, t=t, stride=s))
                last_channels = int(1280 * multiplier) if multiplier > 1.0 \
                    else 1280
                _add_conv(self.features, last_channels, relu6=True)
                self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.HybridSequential(prefix='output_')
            with self.output.name_scope():
                self.output.add(
                    nn.Conv2D(classes, 1, use_bias=False, prefix='pred_'),
                    nn.Flatten())

    def hybrid_forward(self, F, x):
        x = self._stem_input(F, x)
        x = self.features(x)
        return self.output(x)


def get_mobilenet(multiplier, pretrained=False, ctx=cpu(),
                  root=os.path.join('~', '.mxnet', 'models'), **kwargs):
    if pretrained:
        # shipped checkpoints are reference-layout (NCHW/OIHW)
        kwargs.setdefault('layout', 'NCHW')
    net = MobileNet(multiplier, **kwargs)
    if pretrained:
        version_suffix = '{0:.2f}'.format(multiplier)
        if version_suffix in ('1.00', '0.50'):
            version_suffix = version_suffix[:-1]
        net.load_parameters(os.path.join(
            os.path.expanduser(root),
            'mobilenet%s.params' % version_suffix), ctx=ctx)
    return net


def get_mobilenet_v2(multiplier, pretrained=False, ctx=cpu(),
                     root=os.path.join('~', '.mxnet', 'models'), **kwargs):
    if pretrained:
        # shipped checkpoints are reference-layout (NCHW/OIHW)
        kwargs.setdefault('layout', 'NCHW')
    net = MobileNetV2(multiplier, **kwargs)
    if pretrained:
        version_suffix = '{0:.2f}'.format(multiplier)
        if version_suffix in ('1.00', '0.50'):
            version_suffix = version_suffix[:-1]
        net.load_parameters(os.path.join(
            os.path.expanduser(root),
            'mobilenetv2_%s.params' % version_suffix), ctx=ctx)
    return net


def mobilenet1_0(**kwargs):
    return get_mobilenet(1.0, **kwargs)


def mobilenet0_75(**kwargs):
    return get_mobilenet(0.75, **kwargs)


def mobilenet0_5(**kwargs):
    return get_mobilenet(0.5, **kwargs)


def mobilenet0_25(**kwargs):
    return get_mobilenet(0.25, **kwargs)


def mobilenet_v2_1_0(**kwargs):
    return get_mobilenet_v2(1.0, **kwargs)


def mobilenet_v2_0_75(**kwargs):
    return get_mobilenet_v2(0.75, **kwargs)


def mobilenet_v2_0_5(**kwargs):
    return get_mobilenet_v2(0.5, **kwargs)


def mobilenet_v2_0_25(**kwargs):
    return get_mobilenet_v2(0.25, **kwargs)
