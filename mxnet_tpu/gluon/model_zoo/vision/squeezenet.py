"""SqueezeNet 1.0/1.1 (parity: gluon/model_zoo/vision/squeezenet.py)."""
from __future__ import annotations

import os

from ... import nn
from .... import layout as layout_mod
from ....context import cpu
from ._base import _LayoutNet


def _make_fire(squeeze_channels, expand1x1_channels, expand3x3_channels):
    out = nn.HybridSequential(prefix='')
    out.add(_make_fire_conv(squeeze_channels, 1))
    paths = nn.HybridConcurrent(
        axis=layout_mod.current_channel_axis(), prefix='')
    paths.add(_make_fire_conv(expand1x1_channels, 1))
    paths.add(_make_fire_conv(expand3x3_channels, 3, 1))
    out.add(paths)
    return out


def _make_fire_conv(channels, kernel_size, padding=0):
    out = nn.HybridSequential(prefix='')
    out.add(nn.Conv2D(channels, kernel_size, padding=padding))
    out.add(nn.Activation('relu'))
    return out


class SqueezeNet(_LayoutNet):
    def __init__(self, version, classes=1000, layout=None, **kwargs):
        super().__init__(layout=layout, **kwargs)
        assert version in ['1.0', '1.1'], \
            "Unsupported SqueezeNet version {}: 1.0 or 1.1 expected".format(
                version)
        with self._build_scope(), self.name_scope():
            self.features = nn.HybridSequential(prefix='')
            if version == '1.0':
                self.features.add(nn.Conv2D(96, kernel_size=7, strides=2))
                self.features.add(nn.Activation('relu'))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                               ceil_mode=True))
                self.features.add(_make_fire(16, 64, 64))
                self.features.add(_make_fire(16, 64, 64))
                self.features.add(_make_fire(32, 128, 128))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                               ceil_mode=True))
                self.features.add(_make_fire(32, 128, 128))
                self.features.add(_make_fire(48, 192, 192))
                self.features.add(_make_fire(48, 192, 192))
                self.features.add(_make_fire(64, 256, 256))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                               ceil_mode=True))
                self.features.add(_make_fire(64, 256, 256))
            else:
                self.features.add(nn.Conv2D(64, kernel_size=3, strides=2))
                self.features.add(nn.Activation('relu'))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                               ceil_mode=True))
                self.features.add(_make_fire(16, 64, 64))
                self.features.add(_make_fire(16, 64, 64))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                               ceil_mode=True))
                self.features.add(_make_fire(32, 128, 128))
                self.features.add(_make_fire(32, 128, 128))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                               ceil_mode=True))
                self.features.add(_make_fire(48, 192, 192))
                self.features.add(_make_fire(48, 192, 192))
                self.features.add(_make_fire(64, 256, 256))
                self.features.add(_make_fire(64, 256, 256))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.HybridSequential(prefix='')
            self.output.add(nn.Conv2D(classes, kernel_size=1))
            self.output.add(nn.Activation('relu'))
            self.output.add(nn.GlobalAvgPool2D())
            self.output.add(nn.Flatten())

    def hybrid_forward(self, F, x):
        x = self._stem_input(F, x)
        x = self.features(x)
        return self.output(x)


def get_squeezenet(version, pretrained=False, ctx=cpu(),
                   root=os.path.join('~', '.mxnet', 'models'), **kwargs):
    if pretrained:
        # shipped checkpoints are reference-layout (NCHW/OIHW)
        kwargs.setdefault('layout', 'NCHW')
    net = SqueezeNet(version, **kwargs)
    if pretrained:
        net.load_parameters(os.path.join(
            os.path.expanduser(root),
            'squeezenet%s.params' % version), ctx=ctx)
    return net


def squeezenet1_0(**kwargs):
    return get_squeezenet('1.0', **kwargs)


def squeezenet1_1(**kwargs):
    return get_squeezenet('1.1', **kwargs)
