"""Vision model zoo (parity: gluon/model_zoo/vision/__init__.py).

All the reference's architecture families, defined natively on
``mxnet_tpu.gluon.nn`` layers: AlexNet, DenseNet, Inception-v3, MobileNet
(v1/v2), ResNet (v1/v2, 18-152), SqueezeNet, VGG (11-19, ±BN).
No pretrained weights are shipped (no egress): ``pretrained=True`` loads
from a local ``root`` directory when the .params file exists there.
"""
from .alexnet import alexnet, AlexNet  # noqa: F401
from .densenet import (  # noqa: F401
    DenseNet, densenet121, densenet161, densenet169, densenet201,
)
from .inception import inception_v3, Inception3  # noqa: F401
from .mobilenet import (  # noqa: F401
    MobileNet, MobileNetV2,
    mobilenet1_0, mobilenet0_75, mobilenet0_5, mobilenet0_25,
    mobilenet_v2_1_0, mobilenet_v2_0_75, mobilenet_v2_0_5,
    mobilenet_v2_0_25,
)
from .resnet import (  # noqa: F401
    ResNetV1, ResNetV2, BasicBlockV1, BasicBlockV2,
    BottleneckV1, BottleneckV2, get_resnet,
    resnet18_v1, resnet34_v1, resnet50_v1, resnet101_v1, resnet152_v1,
    resnet18_v2, resnet34_v2, resnet50_v2, resnet101_v2, resnet152_v2,
)
from .squeezenet import SqueezeNet, squeezenet1_0, squeezenet1_1  # noqa
from .vgg import (  # noqa: F401
    VGG, vgg11, vgg13, vgg16, vgg19,
    vgg11_bn, vgg13_bn, vgg16_bn, vgg19_bn,
)

from ....base import MXNetError

_models = {
    'resnet18_v1': resnet18_v1, 'resnet34_v1': resnet34_v1,
    'resnet50_v1': resnet50_v1, 'resnet101_v1': resnet101_v1,
    'resnet152_v1': resnet152_v1,
    'resnet18_v2': resnet18_v2, 'resnet34_v2': resnet34_v2,
    'resnet50_v2': resnet50_v2, 'resnet101_v2': resnet101_v2,
    'resnet152_v2': resnet152_v2,
    'vgg11': vgg11, 'vgg13': vgg13, 'vgg16': vgg16, 'vgg19': vgg19,
    'vgg11_bn': vgg11_bn, 'vgg13_bn': vgg13_bn, 'vgg16_bn': vgg16_bn,
    'vgg19_bn': vgg19_bn,
    'alexnet': alexnet,
    'densenet121': densenet121, 'densenet161': densenet161,
    'densenet169': densenet169, 'densenet201': densenet201,
    'squeezenet1.0': squeezenet1_0, 'squeezenet1.1': squeezenet1_1,
    'inceptionv3': inception_v3,
    'mobilenet1.0': mobilenet1_0, 'mobilenet0.75': mobilenet0_75,
    'mobilenet0.5': mobilenet0_5, 'mobilenet0.25': mobilenet0_25,
    'mobilenetv2_1.0': mobilenet_v2_1_0,
    'mobilenetv2_0.75': mobilenet_v2_0_75,
    'mobilenetv2_0.5': mobilenet_v2_0_5,
    'mobilenetv2_0.25': mobilenet_v2_0_25,
}


def get_model(name, **kwargs):
    """Create a model by name (parity: model_zoo/vision get_model)."""
    name = name.lower()
    if name not in _models:
        raise MXNetError(
            "Model %s is not supported. Available: %s"
            % (name, sorted(_models)))
    return _models[name](**kwargs)
