"""Inception v3 (parity: gluon/model_zoo/vision/inception.py)."""
from __future__ import annotations

import os

from ...block import HybridBlock
from ... import nn
from .... import layout as layout_mod
from ....context import cpu
from ._base import _LayoutNet


def _make_basic_conv(**kwargs):
    out = nn.HybridSequential(prefix='')
    out.add(nn.Conv2D(use_bias=False, **kwargs))
    out.add(nn.BatchNorm(epsilon=0.001))
    out.add(nn.Activation('relu'))
    return out


def _make_branch(use_pool, *conv_settings):
    out = nn.HybridSequential(prefix='')
    if use_pool == 'avg':
        out.add(nn.AvgPool2D(pool_size=3, strides=1, padding=1))
    elif use_pool == 'max':
        out.add(nn.MaxPool2D(pool_size=3, strides=2))
    setting_names = ['channels', 'kernel_size', 'strides', 'padding']
    for setting in conv_settings:
        kwargs = {}
        for i, value in enumerate(setting):
            if value is not None:
                kwargs[setting_names[i]] = value
        out.add(_make_basic_conv(**kwargs))
    return out


def _make_A(pool_features, prefix):
    out = nn.HybridConcurrent(axis=layout_mod.current_channel_axis(),
                              prefix=prefix)
    with out.name_scope():
        out.add(_make_branch(None, (64, 1, None, None)))
        out.add(_make_branch(None, (48, 1, None, None),
                             (64, 5, None, 2)))
        out.add(_make_branch(None, (64, 1, None, None),
                             (96, 3, None, 1), (96, 3, None, 1)))
        out.add(_make_branch('avg', (pool_features, 1, None, None)))
    return out


def _make_B(prefix):
    out = nn.HybridConcurrent(axis=layout_mod.current_channel_axis(),
                              prefix=prefix)
    with out.name_scope():
        out.add(_make_branch(None, (384, 3, 2, None)))
        out.add(_make_branch(None, (64, 1, None, None),
                             (96, 3, None, 1), (96, 3, 2, None)))
        out.add(_make_branch('max'))
    return out


def _make_C(channels_7x7, prefix):
    out = nn.HybridConcurrent(axis=layout_mod.current_channel_axis(),
                              prefix=prefix)
    with out.name_scope():
        out.add(_make_branch(None, (192, 1, None, None)))
        out.add(_make_branch(
            None, (channels_7x7, 1, None, None),
            (channels_7x7, (1, 7), None, (0, 3)),
            (192, (7, 1), None, (3, 0))))
        out.add(_make_branch(
            None, (channels_7x7, 1, None, None),
            (channels_7x7, (7, 1), None, (3, 0)),
            (channels_7x7, (1, 7), None, (0, 3)),
            (channels_7x7, (7, 1), None, (3, 0)),
            (192, (1, 7), None, (0, 3))))
        out.add(_make_branch('avg', (192, 1, None, None)))
    return out


def _make_D(prefix):
    out = nn.HybridConcurrent(axis=layout_mod.current_channel_axis(),
                              prefix=prefix)
    with out.name_scope():
        out.add(_make_branch(None, (192, 1, None, None),
                             (320, 3, 2, None)))
        out.add(_make_branch(
            None, (192, 1, None, None), (192, (1, 7), None, (0, 3)),
            (192, (7, 1), None, (3, 0)), (192, 3, 2, None)))
        out.add(_make_branch('max'))
    return out


class _SplitConcat(HybridBlock):
    """Two parallel convs over one input, concat (inception E tail)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.branches = None
        self._caxis = layout_mod.current_channel_axis()

    def hybrid_forward(self, F, x):
        return F.concat(*[b(x) for b in self._children.values()],
                        dim=self._caxis)


def _make_E(prefix):
    out = nn.HybridConcurrent(axis=layout_mod.current_channel_axis(),
                              prefix=prefix)
    with out.name_scope():
        out.add(_make_branch(None, (320, 1, None, None)))

        branch_3x3 = nn.HybridSequential(prefix='')
        out.add(branch_3x3)
        branch_3x3.add(_make_branch(None, (384, 1, None, None)))
        branch_3x3_split = _SplitConcat()
        branch_3x3_split.register_child(
            _make_branch(None, (384, (1, 3), None, (0, 1))), 'a')
        branch_3x3_split.register_child(
            _make_branch(None, (384, (3, 1), None, (1, 0))), 'b')
        branch_3x3.add(branch_3x3_split)

        branch_3x3dbl = nn.HybridSequential(prefix='')
        out.add(branch_3x3dbl)
        branch_3x3dbl.add(_make_branch(None, (448, 1, None, None),
                                       (384, 3, None, 1)))
        branch_3x3dbl_split = _SplitConcat()
        branch_3x3dbl_split.register_child(
            _make_branch(None, (384, (1, 3), None, (0, 1))), 'a')
        branch_3x3dbl_split.register_child(
            _make_branch(None, (384, (3, 1), None, (1, 0))), 'b')
        branch_3x3dbl.add(branch_3x3dbl_split)

        out.add(_make_branch('avg', (192, 1, None, None)))
    return out


class Inception3(_LayoutNet):
    """Inception v3 (parity: inception.py Inception3:119)."""

    def __init__(self, classes=1000, layout=None, **kwargs):
        super().__init__(layout=layout, **kwargs)
        with self._build_scope(), self.name_scope():
            self.features = nn.HybridSequential(prefix='')
            self.features.add(_make_basic_conv(
                channels=32, kernel_size=3, strides=2))
            self.features.add(_make_basic_conv(channels=32, kernel_size=3))
            self.features.add(_make_basic_conv(
                channels=64, kernel_size=3, padding=1))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
            self.features.add(_make_basic_conv(channels=80, kernel_size=1))
            self.features.add(_make_basic_conv(channels=192,
                                               kernel_size=3))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
            self.features.add(_make_A(32, 'A1_'))
            self.features.add(_make_A(64, 'A2_'))
            self.features.add(_make_A(64, 'A3_'))
            self.features.add(_make_B('B_'))
            self.features.add(_make_C(128, 'C1_'))
            self.features.add(_make_C(160, 'C2_'))
            self.features.add(_make_C(160, 'C3_'))
            self.features.add(_make_C(192, 'C4_'))
            self.features.add(_make_D('D_'))
            self.features.add(_make_E('E1_'))
            self.features.add(_make_E('E2_'))
            self.features.add(nn.AvgPool2D(pool_size=8))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self._stem_input(F, x)
        x = self.features(x)
        return self.output(x)


def inception_v3(pretrained=False, ctx=cpu(),
                 root=os.path.join('~', '.mxnet', 'models'), **kwargs):
    if pretrained:
        # shipped checkpoints are reference-layout (NCHW/OIHW)
        kwargs.setdefault('layout', 'NCHW')
    net = Inception3(**kwargs)
    if pretrained:
        net.load_parameters(os.path.join(
            os.path.expanduser(root), 'inceptionv3.params'), ctx=ctx)
    return net
