"""Model zoo (parity: ``python/mxnet/gluon/model_zoo/``)."""
from . import vision  # noqa: F401
from . import bert  # noqa: F401
from . import llama  # noqa: F401
from .vision import get_model  # noqa: F401
