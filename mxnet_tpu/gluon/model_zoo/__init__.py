"""Model zoo (parity: ``python/mxnet/gluon/model_zoo/``)."""
from . import vision  # noqa: F401
from .vision import get_model  # noqa: F401
