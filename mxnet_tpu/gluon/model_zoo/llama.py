"""Llama-family decoder-only language models.

SURVEY §7 stretch config: the reference era has no decoder-LM counterpart
(its transformer support stops at fused attention matmuls,
``src/operator/contrib/transformer.cc``), so this family is designed
TPU-first rather than ported:

- attention runs through the Pallas flash-attention op
  (``_contrib_flash_attention`` — blockwise online softmax on the MXU),
- RoPE is computed inside the traced graph (static T ⇒ XLA constant-folds
  the tables into the executable),
- grouped-query attention (GQA) keeps the KV projection small and the
  repeat happens post-projection, where XLA fuses it into the attention,
- the whole model is a HybridBlock: one XLA executable under
  ``hybridize()``/``JitTrainStep``; weights cast to bf16 via
  ``net.cast('bfloat16')`` or AMP keep every matmul MXU-native.

Long sequences: q/k/v from these blocks drop directly into
``parallel.ring_attention_sharded`` to shard T across chips over an
``sp`` mesh axis (SURVEY §5.7 long-context design); tensor-parallel
sharding of the FFN/attention projections comes from
``parallel.JitTrainStep(param_rule=...)`` over a ``model`` axis.
"""
from __future__ import annotations

import math

from .. import nn
from ..block import HybridBlock


def _clear_caches(block):
    """Drop hybridize caches across the whole tree (the kernel choice is
    baked into compiled executables, so toggles must invalidate them)."""
    block.apply(lambda b: b.clear_cache()
                if hasattr(b, "clear_cache") else None)


class RMSNorm(HybridBlock):
    """Root-mean-square norm (no mean subtraction), Llama convention."""

    def __init__(self, units, eps=1e-6, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._eps = eps
        self.weight = self.params.get("weight", shape=(units,), init="ones",
                                      allow_deferred_init=False)

    def hybrid_forward(self, F, x, weight=None):
        return F.RMSNorm(x, weight, axis=-1, eps=self._eps)


def _rope(F, x, base=10000.0):
    """Rotary position embedding on (B, H, T, D); rotate-half convention."""
    b, h, t, d = x.shape
    half = d // 2
    inv = F.arange(0, half, dtype="float32") * (-2.0 / d)
    inv_freq = F.exp(inv * math.log(base))            # (half,)
    pos = F.arange(0, t, dtype="float32")             # (T,)
    freqs = F.reshape(pos, shape=(t, 1)) * F.reshape(inv_freq,
                                                     shape=(1, half))
    cos = F.reshape(F.cos(freqs), shape=(1, 1, t, half))
    sin = F.reshape(F.sin(freqs), shape=(1, 1, t, half))
    x1 = F.slice_axis(x, axis=3, begin=0, end=half)
    x2 = F.slice_axis(x, axis=3, begin=half, end=d)
    return F.concat(x1 * cos - x2 * sin, x2 * cos + x1 * sin, dim=3)


class LlamaAttention(HybridBlock):
    """Causal self-attention with RoPE and grouped-query KV heads.

    ``sequence_parallel(mesh, axis_name)`` switches the attention kernel
    from the single-chip Pallas flash attention to
    ``parallel.ring_attention_sharded``: Q stays resident per chip while
    K/V blocks travel the ICI ring (ppermute) with an online softmax —
    the long-context design of SURVEY §5.7.  The mesh axis size must
    divide the sequence length T.
    """

    def __init__(self, units, num_heads, num_kv_heads=None,
                 rope_base=10000.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        num_kv_heads = num_kv_heads or num_heads
        if units % num_heads or num_heads % num_kv_heads:
            raise ValueError("units/num_heads/num_kv_heads mismatch")
        self._units = units
        self._heads = num_heads
        self._kv_heads = num_kv_heads
        self._base = rope_base
        self._sp = None  # (mesh, axis_name) when sequence-parallel
        d = units // num_heads
        with self.name_scope():
            self.q_proj = nn.Dense(units, flatten=False, use_bias=False,
                                   prefix="q_")
            self.k_proj = nn.Dense(num_kv_heads * d, flatten=False,
                                   use_bias=False, prefix="k_")
            self.v_proj = nn.Dense(num_kv_heads * d, flatten=False,
                                   use_bias=False, prefix="v_")
            self.o_proj = nn.Dense(units, flatten=False, use_bias=False,
                                   prefix="o_")

    def hybrid_forward(self, F, x):
        b, t, _ = x.shape
        h, kv, d = self._heads, self._kv_heads, self._units // self._heads
        q = F.transpose(F.reshape(self.q_proj(x), shape=(b, t, h, d)),
                        axes=(0, 2, 1, 3))
        k = F.transpose(F.reshape(self.k_proj(x), shape=(b, t, kv, d)),
                        axes=(0, 2, 1, 3))
        v = F.transpose(F.reshape(self.v_proj(x), shape=(b, t, kv, d)),
                        axes=(0, 2, 1, 3))
        q = _rope(F, q, self._base)
        k = _rope(F, k, self._base)
        if kv != h:
            # GQA: repeat each KV head h//kv times (XLA fuses the
            # broadcast into the attention matmuls)
            k = F.repeat(k, repeats=h // kv, axis=1)
            v = F.repeat(v, repeats=h // kv, axis=1)
        if self._sp is not None:
            out = self._ring_attention(q, k, v, 1.0 / math.sqrt(d))
        else:
            out = F.contrib.flash_attention(
                q, k, v, scale=1.0 / math.sqrt(d), causal=True)
        out = F.reshape(F.transpose(out, axes=(0, 2, 1, 3)),
                        shape=(b, t, self._units))
        return self.o_proj(out)

    def sequence_parallel(self, mesh, axis_name="sp"):
        """Enable ring attention over ``axis_name`` of ``mesh`` (pass
        ``None`` to return to flash attention).

        Clears THIS block's hybridize cache only.  When the attention
        sits inside a hybridized parent (the usual case), the compiled
        graph lives on that parent — toggle through
        ``LlamaModel.sequence_parallel``, which invalidates the whole
        tree, or call ``parent.clear_cache()`` yourself."""
        self._sp = None if mesh is None else (mesh, axis_name)
        if hasattr(self, "clear_cache"):
            self.clear_cache()
        return self

    def _ring_attention(self, q, k, v, scale):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec, \
            SingleDeviceSharding

        from ...ops.registry import invoke_fn
        from ...parallel import ring_attention_sharded

        mesh, axis = self._sp
        # three tape nodes: scatter -> ring -> gather.  The scatter/
        # gather are plain device_put (differentiable, trace-safe); by
        # the time the ring's shard_map records its tape node, the
        # stored primals are ALREADY mesh-sharded, so the backward
        # re-trace (jax.vjp over the stored primals) sees correctly
        # placed arrays.  Under a fully jitted multi-chip train step the
        # device_puts become GSPMD sharding constraints.
        sh_in = NamedSharding(mesh, PartitionSpec(None, None, axis, None))
        sh_out = SingleDeviceSharding(list(mesh.devices.flat)[0])
        q, k, v = invoke_fn(
            lambda qq, kk, vv: tuple(jax.device_put(x, sh_in)
                                     for x in (qq, kk, vv)),
            [q, k, v], op_name="ring_scatter")
        (out,) = invoke_fn(
            lambda qq, kk, vv: (ring_attention_sharded(
                qq, kk, vv, mesh, axis_name=axis, scale=scale,
                causal=True),),
            [q, k, v], op_name="ring_attention")
        (out,) = invoke_fn(
            lambda o: (jax.device_put(o, sh_out),), [out],
            op_name="ring_gather")
        return out


class LlamaFFN(HybridBlock):
    """SwiGLU feed-forward: down(silu(gate(x)) * up(x))."""

    def __init__(self, units, hidden_size, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.gate = nn.Dense(hidden_size, flatten=False, use_bias=False,
                                 prefix="gate_")
            self.up = nn.Dense(hidden_size, flatten=False, use_bias=False,
                               prefix="up_")
            self.down = nn.Dense(units, flatten=False, use_bias=False,
                                 prefix="down_")

    def hybrid_forward(self, F, x):
        return self.down(F.Activation(self.gate(x), act_type="silu")
                         * self.up(x))


class LlamaBlock(HybridBlock):
    """Pre-norm decoder block: x + attn(norm(x)); x + ffn(norm(x))."""

    def __init__(self, units, hidden_size, num_heads, num_kv_heads=None,
                 rope_base=10000.0, eps=1e-6, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.attn_norm = RMSNorm(units, eps, prefix="attnorm_")
            self.attn = LlamaAttention(units, num_heads, num_kv_heads,
                                       rope_base, prefix="attn_")
            self.ffn_norm = RMSNorm(units, eps, prefix="ffnnorm_")
            self.ffn = LlamaFFN(units, hidden_size, prefix="ffn_")

    def hybrid_forward(self, F, x):
        x = x + self.attn(self.attn_norm(x))
        return x + self.ffn(self.ffn_norm(x))


class LlamaModel(HybridBlock):
    """Decoder-only LM.  forward(tokens (B,T)) → logits (B,T,V).

    ``sequence_parallel(mesh, axis)`` flips every attention layer to the
    ring-attention kernel for long-context training across chips."""

    def __init__(self, vocab_size, units=4096, hidden_size=11008,
                 num_layers=32, num_heads=32, num_kv_heads=None,
                 rope_base=10000.0, eps=1e-6, tie_embeddings=False,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._tie = tie_embeddings
        with self.name_scope():
            self.embed = nn.Embedding(vocab_size, units, prefix="embed_")
            self.blocks = nn.HybridSequential(prefix="blocks_")
            for i in range(num_layers):
                self.blocks.add(LlamaBlock(
                    units, hidden_size, num_heads, num_kv_heads,
                    rope_base, eps, prefix="block%d_" % i))
            self.norm = RMSNorm(units, eps, prefix="norm_")
            if not tie_embeddings:
                self.lm_head = nn.Dense(vocab_size, flatten=False,
                                        use_bias=False, prefix="head_")

    def sequence_parallel(self, mesh, axis_name="sp"):
        for blk in self.blocks._children.values():
            blk.attn.sequence_parallel(mesh, axis_name)
        _clear_caches(self)  # the model-level compiled graph is stale too
        return self

    def hybrid_forward(self, F, tokens):
        x = self.embed(tokens)
        x = self.blocks(x)
        x = self.norm(x)
        if self._tie:
            b, t, c = x.shape
            w = self.embed.weight.data()
            logits = F.dot(F.reshape(x, shape=(b * t, c)), w,
                           transpose_b=True)
            return F.reshape(logits, shape=(b, t, -1))
        return self.lm_head(x)


def llama3_8b(vocab_size=128256, **kwargs):
    """Llama-3-8B geometry: 32 layers, 4096 units, GQA 32/8 heads."""
    cfg = dict(units=4096, hidden_size=14336, num_layers=32, num_heads=32,
               num_kv_heads=8, rope_base=500000.0)
    cfg.update(kwargs)
    return LlamaModel(vocab_size, **cfg)


def llama2_7b(vocab_size=32000, **kwargs):
    """Llama-2-7B geometry: 32 layers, 4096 units, MHA."""
    cfg = dict(units=4096, hidden_size=11008, num_layers=32, num_heads=32)
    cfg.update(kwargs)
    return LlamaModel(vocab_size, **cfg)


def llama_small(vocab_size=512, **kwargs):
    """Tiny config for tests / dry-runs."""
    cfg = dict(units=64, hidden_size=128, num_layers=2, num_heads=4,
               num_kv_heads=2)
    cfg.update(kwargs)
    return LlamaModel(vocab_size, **cfg)
