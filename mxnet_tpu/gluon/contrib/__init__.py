"""gluon.contrib (parity: python/mxnet/gluon/contrib/)."""
from . import estimator  # noqa: F401
from . import nn  # noqa: F401
from . import rnn  # noqa: F401
from . import cnn  # noqa: F401
from . import data  # noqa: F401
