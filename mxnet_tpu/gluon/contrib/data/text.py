"""Language-model text datasets (parity: gluon/contrib/data/text.py).

The reference downloads WikiText archives; this environment has no
network egress, so the datasets read the SAME files from ``root`` (the
reference's extracted cache layout: ``wiki.train.tokens`` etc.) and
raise a clear error when absent.  Tokenization, vocabulary mapping and
sequence batching match the reference: the corpus becomes one long id
stream split into ``seq_len``-sized (data, label-shifted-by-one)
samples.
"""
from __future__ import annotations

import io
import os

import numpy as np

from ....base import MXNetError
from ...data.dataset import SimpleDataset
from ....contrib.text.vocab import Vocabulary


class _LanguageModelDataset(SimpleDataset):
    """Token-stream dataset over a local corpus file."""

    def __init__(self, path, seq_len=35, vocab=None, eos="<eos>"):
        path = os.path.expanduser(path)
        if not os.path.isfile(path):
            raise MXNetError(
                "corpus file %s not found; this environment has no "
                "network access — place the extracted tokens file there "
                "first" % path)
        with io.open(path, "r", encoding="utf-8") as f:
            raw = f.read()
        lines = [line.split() + [eos] for line in raw.splitlines()
                 if line.strip()]
        if vocab is None:
            import collections

            counter = collections.Counter(
                t for line in lines for t in line)
            vocab = Vocabulary(counter)
        self.vocabulary = vocab
        stream = []
        for line in lines:
            stream.extend(vocab.to_indices(line))
        n = (len(stream) - 1) // seq_len
        data = np.asarray(stream[:n * seq_len + 1], np.int32)
        xs = data[:n * seq_len].reshape(n, seq_len)
        ys = data[1:n * seq_len + 1].reshape(n, seq_len)
        super().__init__([(x, y) for x, y in zip(xs, ys)])
        self.seq_len = seq_len


class WikiText2(_LanguageModelDataset):
    """WikiText-2 (parity: text.py:105).  ``root`` must contain the
    extracted ``wiki.<segment>.tokens`` file."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "wikitext-2"),
                 segment="train", seq_len=35, vocab=None):
        path = os.path.join(os.path.expanduser(root),
                            "wiki.%s.tokens" % segment)
        super().__init__(path, seq_len=seq_len, vocab=vocab)


class WikiText103(_LanguageModelDataset):
    """WikiText-103 (parity: text.py:143); same local-cache contract."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "wikitext-103"),
                 segment="train", seq_len=35, vocab=None):
        path = os.path.join(os.path.expanduser(root),
                            "wiki.%s.tokens" % segment)
        super().__init__(path, seq_len=seq_len, vocab=vocab)
