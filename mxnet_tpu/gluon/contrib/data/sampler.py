"""Contrib samplers (parity: gluon/contrib/data/sampler.py)."""
from __future__ import annotations

from ...data.sampler import Sampler


class IntervalSampler(Sampler):
    """Samples [0, length) at fixed intervals (parity: sampler.py:25).

    With ``rollover`` (default) the sweep restarts at each skipped
    offset until every index is visited exactly once.
    """

    def __init__(self, length, interval, rollover=True):
        assert interval <= length, \
            "Interval {} must be smaller than or equal to length {}" \
            .format(interval, length)
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        for i in range(self._interval if self._rollover else 1):
            yield from range(i, self._length, self._interval)

    def __len__(self):
        return self._length if self._rollover else \
            len(range(0, self._length, self._interval))
