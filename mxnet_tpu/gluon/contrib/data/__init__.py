"""Contrib data utilities (parity: gluon/contrib/data/)."""
from . import text  # noqa: F401
from .sampler import IntervalSampler  # noqa: F401
from .text import WikiText2, WikiText103  # noqa: F401
