"""Contrib recurrent cells (parity: gluon/contrib/rnn/rnn_cell.py).

``VariationalDropoutCell`` — one dropout mask shared across time steps
(Gal & Ghahramani 2016) for inputs/states/outputs; ``LSTMPCell`` — LSTM
with a recurrent projection (Sak et al. 2014).
"""
from __future__ import annotations

from ..rnn.rnn_cell import ModifierCell, HybridRecurrentCell, \
    BidirectionalCell, SequentialRNNCell
from ..block import HybridBlock  # noqa: F401  (re-export convenience)


class VariationalDropoutCell(ModifierCell):
    """Variational dropout over a base cell (parity:
    contrib/rnn/rnn_cell.py:27).  Masks are drawn once per sequence
    (first step after ``reset``) and reused every step; input, state and
    output masks are independent."""

    def __init__(self, base_cell, drop_inputs=0., drop_states=0.,
                 drop_outputs=0.):
        assert not drop_states or not isinstance(base_cell,
                                                 BidirectionalCell), \
            "BidirectionalCell doesn't support variational state " \
            "dropout; wrap the cells underneath instead."
        assert not drop_states or not isinstance(base_cell,
                                                 SequentialRNNCell), \
            "Apply VariationalDropoutCell to the cells underneath the " \
            "SequentialRNNCell instead."
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def _initialize_input_masks(self, F, inputs, states):
        if self.drop_states and self.drop_states_mask is None:
            self.drop_states_mask = F.Dropout(
                F.ones_like(states[0]), p=self.drop_states)
        if self.drop_inputs and self.drop_inputs_mask is None:
            self.drop_inputs_mask = F.Dropout(
                F.ones_like(inputs), p=self.drop_inputs)

    def _initialize_output_mask(self, F, output):
        if self.drop_outputs and self.drop_outputs_mask is None:
            self.drop_outputs_mask = F.Dropout(
                F.ones_like(output), p=self.drop_outputs)

    def hybrid_forward(self, F, inputs, states):
        cell = self.base_cell
        self._initialize_input_masks(F, inputs, states)
        if self.drop_states:
            states = list(states)
            # state dropout applies to the first state channel only
            # (reference semantics)
            states[0] = states[0] * self.drop_states_mask
        if self.drop_inputs:
            inputs = inputs * self.drop_inputs_mask
        next_output, next_states = cell(inputs, states)
        self._initialize_output_mask(F, next_output)
        if self.drop_outputs:
            next_output = next_output * self.drop_outputs_mask
        return next_output, next_states

    def __repr__(self):
        return "VariationalDropoutCell(p_out=%s, p_state=%s)" % (
            self.drop_outputs, self.drop_states)


class LSTMPCell(HybridRecurrentCell):
    """LSTM with recurrent projection (parity:
    contrib/rnn/rnn_cell.py:197; arXiv:1402.1128).

    States are [projected (B, P), cell (B, H)]; the hidden state is
    projected to P units before recurrence and output.
    """

    def __init__(self, hidden_size, projection_size,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None,
                 i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._projection_size = projection_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, projection_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.h2r_weight = self.params.get(
            "h2r_weight", shape=(projection_size, hidden_size),
            init=h2r_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def _alias(self):
        return "lstmp"

    def state_info(self, batch_size=0):
        return [
            {"shape": (batch_size, self._projection_size),
             "__layout__": "NC"},
            {"shape": (batch_size, self._hidden_size),
             "__layout__": "NC"},
        ]

    def _shape_hint(self, x, *args):
        if self.i2h_weight.shape and self.i2h_weight.shape[1] == 0:
            self._input_size = x.shape[-1]
            self.i2h_weight.shape = (4 * self._hidden_size,
                                     self._input_size)

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       h2r_weight, i2h_bias, h2h_bias):
        prefix = "t%d_" % getattr(self, "_counter", 0)
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size,
                               name=prefix + "i2h")
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size,
                               name=prefix + "h2h")
        gates = i2h + h2h
        sliced = F.SliceChannel(gates, num_outputs=4,
                                name=prefix + "slice")
        sliced = list(sliced) if not isinstance(sliced, (list, tuple)) \
            else sliced
        in_gate = F.Activation(sliced[0], act_type="sigmoid")
        forget_gate = F.Activation(sliced[1], act_type="sigmoid")
        in_transform = F.Activation(sliced[2], act_type="tanh")
        out_gate = F.Activation(sliced[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        hidden = out_gate * F.Activation(next_c, act_type="tanh")
        next_r = F.FullyConnected(hidden, h2r_weight, None, no_bias=True,
                                  num_hidden=self._projection_size,
                                  name=prefix + "out")
        return next_r, [next_r, next_c]
