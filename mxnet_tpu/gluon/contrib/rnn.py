"""Contrib recurrent cells (parity: gluon/contrib/rnn/rnn_cell.py).

``VariationalDropoutCell`` — one dropout mask shared across time steps
(Gal & Ghahramani 2016) for inputs/states/outputs; ``LSTMPCell`` — LSTM
with a recurrent projection (Sak et al. 2014).
"""
from __future__ import annotations

from ..rnn.rnn_cell import ModifierCell, HybridRecurrentCell, \
    BidirectionalCell, SequentialRNNCell
from ..block import HybridBlock  # noqa: F401  (re-export convenience)


class VariationalDropoutCell(ModifierCell):
    """Variational dropout over a base cell (parity:
    contrib/rnn/rnn_cell.py:27).  Masks are drawn once per sequence
    (first step after ``reset``) and reused every step; input, state and
    output masks are independent."""

    def __init__(self, base_cell, drop_inputs=0., drop_states=0.,
                 drop_outputs=0.):
        assert not drop_states or not isinstance(base_cell,
                                                 BidirectionalCell), \
            "BidirectionalCell doesn't support variational state " \
            "dropout; wrap the cells underneath instead."
        assert not drop_states or not isinstance(base_cell,
                                                 SequentialRNNCell), \
            "Apply VariationalDropoutCell to the cells underneath the " \
            "SequentialRNNCell instead."
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def _initialize_input_masks(self, F, inputs, states):
        if self.drop_states and self.drop_states_mask is None:
            self.drop_states_mask = F.Dropout(
                F.ones_like(states[0]), p=self.drop_states)
        if self.drop_inputs and self.drop_inputs_mask is None:
            self.drop_inputs_mask = F.Dropout(
                F.ones_like(inputs), p=self.drop_inputs)

    def _initialize_output_mask(self, F, output):
        if self.drop_outputs and self.drop_outputs_mask is None:
            self.drop_outputs_mask = F.Dropout(
                F.ones_like(output), p=self.drop_outputs)

    def hybrid_forward(self, F, inputs, states):
        cell = self.base_cell
        self._initialize_input_masks(F, inputs, states)
        if self.drop_states:
            states = list(states)
            # state dropout applies to the first state channel only
            # (reference semantics)
            states[0] = states[0] * self.drop_states_mask
        if self.drop_inputs:
            inputs = inputs * self.drop_inputs_mask
        next_output, next_states = cell(inputs, states)
        self._initialize_output_mask(F, next_output)
        if self.drop_outputs:
            next_output = next_output * self.drop_outputs_mask
        return next_output, next_states

    def __repr__(self):
        return "VariationalDropoutCell(p_out=%s, p_state=%s)" % (
            self.drop_outputs, self.drop_states)


class LSTMPCell(HybridRecurrentCell):
    """LSTM with recurrent projection (parity:
    contrib/rnn/rnn_cell.py:197; arXiv:1402.1128).

    States are [projected (B, P), cell (B, H)]; the hidden state is
    projected to P units before recurrence and output.
    """

    def __init__(self, hidden_size, projection_size,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None,
                 i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._projection_size = projection_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, projection_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.h2r_weight = self.params.get(
            "h2r_weight", shape=(projection_size, hidden_size),
            init=h2r_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def _alias(self):
        return "lstmp"

    def state_info(self, batch_size=0):
        return [
            {"shape": (batch_size, self._projection_size),
             "__layout__": "NC"},
            {"shape": (batch_size, self._hidden_size),
             "__layout__": "NC"},
        ]

    def _shape_hint(self, x, *args):
        if self.i2h_weight.shape and self.i2h_weight.shape[1] == 0:
            self._input_size = x.shape[-1]
            self.i2h_weight.shape = (4 * self._hidden_size,
                                     self._input_size)

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       h2r_weight, i2h_bias, h2h_bias):
        prefix = "t%d_" % getattr(self, "_counter", 0)
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size,
                               name=prefix + "i2h")
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size,
                               name=prefix + "h2h")
        gates = i2h + h2h
        sliced = F.SliceChannel(gates, num_outputs=4,
                                name=prefix + "slice")
        sliced = list(sliced) if not isinstance(sliced, (list, tuple)) \
            else sliced
        in_gate = F.Activation(sliced[0], act_type="sigmoid")
        forget_gate = F.Activation(sliced[1], act_type="sigmoid")
        in_transform = F.Activation(sliced[2], act_type="tanh")
        out_gate = F.Activation(sliced[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        hidden = out_gate * F.Activation(next_c, act_type="tanh")
        next_r = F.FullyConnected(hidden, h2r_weight, None, no_bias=True,
                                  num_hidden=self._projection_size,
                                  name=prefix + "out")
        return next_r, [next_r, next_c]


# ---------------------------------------------------------------------------
# Convolutional recurrent cells (parity: gluon/contrib/rnn/conv_rnn_cell.py)
# ---------------------------------------------------------------------------


class _BaseConvCell(HybridRecurrentCell):
    """Recurrent cell whose i2h/h2h transforms are convolutions over
    NC*-layout feature maps (parity: conv_rnn_cell.py:37
    _BaseConvRNNCell).  ``input_shape`` is the per-sample shape
    ``(channels, *spatial)``; the h2h kernel must be odd so its SAME
    padding keeps the state shape."""

    _num_gates = 1

    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, i2h_pad, i2h_dilate, h2h_dilate, dims,
                 activation="tanh", i2h_weight_initializer=None,
                 h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        as_t = lambda v: (v,) * dims if isinstance(v, int) else tuple(v)
        self._dims = dims
        self._input_shape = tuple(input_shape)
        self._hidden_channels = hidden_channels
        self._activation = activation
        self._i2h_kernel = as_t(i2h_kernel)
        self._h2h_kernel = as_t(h2h_kernel)
        assert all(k % 2 == 1 for k in self._h2h_kernel), \
            "h2h_kernel must be odd (SAME-padded state conv), got %s" \
            % (h2h_kernel,)
        self._i2h_pad = as_t(i2h_pad)
        self._i2h_dilate = as_t(i2h_dilate)
        self._h2h_dilate = as_t(h2h_dilate)
        self._h2h_pad = tuple(d * (k - 1) // 2 for d, k in
                              zip(self._h2h_dilate, self._h2h_kernel))
        in_ch = input_shape[0]
        ng = self._num_gates
        # state spatial dims = i2h conv output dims
        self._state_shape = (hidden_channels,) + tuple(
            (x + 2 * p - d * (k - 1) - 1) + 1
            for x, p, d, k in zip(input_shape[1:], self._i2h_pad,
                                  self._i2h_dilate, self._i2h_kernel))
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight",
                shape=(ng * hidden_channels, in_ch) + self._i2h_kernel,
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight",
                shape=(ng * hidden_channels,
                       hidden_channels) + self._h2h_kernel,
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(ng * hidden_channels,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(ng * hidden_channels,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        shape = (batch_size,) + self._state_shape
        return [{"shape": shape, "__layout__": "NC" + "DHW"[-self._dims:]}
                ] * (2 if self._num_gates == 4 else 1)

    def _convs(self, F, inputs, states, i2h_weight, h2h_weight,
               i2h_bias, h2h_bias):
        ng = self._num_gates
        layout = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[self._dims]
        i2h = F.Convolution(
            inputs, i2h_weight, i2h_bias,
            kernel=self._i2h_kernel, pad=self._i2h_pad,
            dilate=self._i2h_dilate, layout=layout,
            num_filter=ng * self._hidden_channels)
        h2h = F.Convolution(
            states[0], h2h_weight, h2h_bias,
            kernel=self._h2h_kernel, pad=self._h2h_pad,
            dilate=self._h2h_dilate, layout=layout,
            num_filter=ng * self._hidden_channels)
        return i2h, h2h

    def _act(self, F, x):
        # same contract as the dense cells: any act_type string the
        # Activation op supports, or a callable block
        return self._get_activation(F, x, self._activation)


class _ConvRNNCellImpl(_BaseConvCell):
    _num_gates = 1

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._convs(F, inputs, states, i2h_weight,
                               h2h_weight, i2h_bias, h2h_bias)
        out = self._act(F, i2h + h2h)
        return out, [out]


class _ConvLSTMCellImpl(_BaseConvCell):
    _num_gates = 4

    def _alias(self):
        return "conv_lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._convs(F, inputs, states, i2h_weight,
                               h2h_weight, i2h_bias, h2h_bias)
        gates = i2h + h2h
        slices = F.SliceChannel(gates, num_outputs=4, axis=1)
        i = F.Activation(slices[0], act_type="sigmoid")
        f = F.Activation(slices[1], act_type="sigmoid")
        c_in = self._act(F, slices[2])
        o = F.Activation(slices[3], act_type="sigmoid")
        next_c = f * states[1] + i * c_in
        next_h = o * self._act(F, next_c)
        return next_h, [next_h, next_c]


class _ConvGRUCellImpl(_BaseConvCell):
    _num_gates = 3

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._convs(F, inputs, states, i2h_weight,
                               h2h_weight, i2h_bias, h2h_bias)
        i2h_s = F.SliceChannel(i2h, num_outputs=3, axis=1)
        h2h_s = F.SliceChannel(h2h, num_outputs=3, axis=1)
        reset = F.Activation(i2h_s[0] + h2h_s[0], act_type="sigmoid")
        update = F.Activation(i2h_s[1] + h2h_s[1], act_type="sigmoid")
        new_mem = self._act(F, i2h_s[2] + reset * h2h_s[2])
        out = (1.0 - update) * new_mem + update * states[0]
        return out, [out]


def _make_conv_cell(impl, dims, name, doc_line):
    class Cell(impl):
        __doc__ = ("%s over %dD feature maps (parity: "
                   "conv_rnn_cell.py %s)." % (doc_line, dims, name))

        def __init__(self, input_shape, hidden_channels, i2h_kernel,
                     h2h_kernel, i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                     activation="tanh", **kwargs):
            super().__init__(input_shape, hidden_channels, i2h_kernel,
                             h2h_kernel, i2h_pad, i2h_dilate, h2h_dilate,
                             dims, activation=activation, **kwargs)

    Cell.__name__ = Cell.__qualname__ = name
    return Cell


Conv1DRNNCell = _make_conv_cell(_ConvRNNCellImpl, 1, "Conv1DRNNCell",
                                "Convolutional vanilla RNN cell")
Conv2DRNNCell = _make_conv_cell(_ConvRNNCellImpl, 2, "Conv2DRNNCell",
                                "Convolutional vanilla RNN cell")
Conv3DRNNCell = _make_conv_cell(_ConvRNNCellImpl, 3, "Conv3DRNNCell",
                                "Convolutional vanilla RNN cell")
Conv1DLSTMCell = _make_conv_cell(_ConvLSTMCellImpl, 1, "Conv1DLSTMCell",
                                 "ConvLSTM cell (Shi et al. 2015)")
Conv2DLSTMCell = _make_conv_cell(_ConvLSTMCellImpl, 2, "Conv2DLSTMCell",
                                 "ConvLSTM cell (Shi et al. 2015)")
Conv3DLSTMCell = _make_conv_cell(_ConvLSTMCellImpl, 3, "Conv3DLSTMCell",
                                 "ConvLSTM cell (Shi et al. 2015)")
Conv1DGRUCell = _make_conv_cell(_ConvGRUCellImpl, 1, "Conv1DGRUCell",
                                "Convolutional GRU cell")
Conv2DGRUCell = _make_conv_cell(_ConvGRUCellImpl, 2, "Conv2DGRUCell",
                                "Convolutional GRU cell")
Conv3DGRUCell = _make_conv_cell(_ConvGRUCellImpl, 3, "Conv3DGRUCell",
                                "Convolutional GRU cell")
