"""Contrib convolution layers (parity: gluon/contrib/cnn/conv_layers.py).

``DeformableConvolution`` wraps the ``_contrib_DeformableConvolution``
operator (ops/vision.py — bilinear sampling at learned offsets) with a
built-in offset-predicting convolution, like the reference layer.
"""
from __future__ import annotations

from ..block import HybridBlock
from .. import nn as _nn


class DeformableConvolution(HybridBlock):
    """Deformable conv v1 (parity: contrib/cnn DeformableConvolution;
    Dai et al. 2017): a standard conv predicts per-position sampling
    offsets for the deformable kernel."""

    def __init__(self, channels, kernel_size=(1, 1), strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), groups=1,
                 num_deformable_group=1, layout="NCHW", use_bias=True,
                 in_channels=0, activation=None,
                 weight_initializer=None, bias_initializer="zeros",
                 offset_weight_initializer="zeros",
                 offset_bias_initializer="zeros", offset_use_bias=True,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * 2
        if isinstance(strides, int):
            strides = (strides,) * 2
        if isinstance(padding, int):
            padding = (padding,) * 2
        if isinstance(dilation, int):
            dilation = (dilation,) * 2
        assert layout == "NCHW", \
            "DeformableConvolution supports NCHW layout only"
        self._channels = channels
        self._kwargs = dict(kernel=kernel_size, stride=strides,
                            pad=padding, dilate=dilation,
                            num_filter=channels, num_group=groups,
                            num_deformable_group=num_deformable_group,
                            no_bias=not use_bias, layout=layout)
        offset_channels = 2 * kernel_size[0] * kernel_size[1] \
            * num_deformable_group
        with self.name_scope():
            self.offset = _nn.Conv2D(
                offset_channels, kernel_size=kernel_size,
                strides=strides, padding=padding, dilation=dilation,
                layout=layout, use_bias=offset_use_bias,
                weight_initializer=offset_weight_initializer,
                bias_initializer=offset_bias_initializer,
                in_channels=in_channels, prefix="offset_")
            self.weight = self.params.get(
                "weight",
                shape=(channels,
                       in_channels // groups if in_channels else 0)
                + tuple(kernel_size),
                init=weight_initializer, allow_deferred_init=True)
            self.bias = self.params.get(
                "bias", shape=(channels,), init=bias_initializer,
                allow_deferred_init=True) if use_bias else None
            self.act = _nn.Activation(activation) \
                if activation is not None else None

    def _shape_hint(self, x, *args):
        if self.weight.shape and 0 in self.weight.shape:
            cin = x.shape[1]
            k = self._kwargs["kernel"]
            g = self._kwargs["num_group"]
            self.weight.shape = (self._channels, cin // g) + tuple(k)

    def hybrid_forward(self, F, x, weight, bias=None):
        offset = self.offset(x)
        out = F._contrib_DeformableConvolution(x, offset, weight, bias,
                                               **self._kwargs)
        if self.act is not None:
            out = self.act(out)
        return out
