"""gluon.contrib.nn — auxiliary layers (parity: gluon/contrib/nn/basic_layers.py).

``SyncBatchNorm`` deserves a note: the reference needs a dedicated
cross-GPU op (``sync_batch_norm.cc``) because each GPU computes batch
stats over its local slice.  Under this framework's GSPMD training
(``parallel.JitTrainStep``), arrays are *logically global* — a plain
BatchNorm's ``mean``/``var`` reduce over the whole sharded batch and
XLA inserts the ICI all-reduce automatically.  SyncBatchNorm is
therefore literally BatchNorm here; the class exists for API parity and
to document the semantics.
"""
from __future__ import annotations

from ..block import HybridBlock
from .. import nn as _nn


class Concurrent(_nn.HybridSequential):
    """Run children on the same input, concat outputs (ref :29)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        outs = [block(x) for block in self._children.values()]
        return F.concat(*outs, dim=self.axis)


class HybridConcurrent(Concurrent):
    """Alias of Concurrent (everything here hybridizes; ref :77)."""


class Identity(HybridBlock):
    """Pass-through block (ref :127) — useful in Concurrent branches."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(_nn.Embedding):
    """Embedding with row_sparse gradients (ref :147).

    Sugar for ``nn.Embedding(..., sparse_grad=True)`` — the gradient is
    a RowSparseNDArray of just the touched rows, applied lazily by the
    optimizer (gather→step→scatter).
    """

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(input_dim, output_dim, dtype=dtype,
                         weight_initializer=weight_initializer,
                         sparse_grad=True, **kwargs)


class SyncBatchNorm(_nn.BatchNorm):
    """Cross-device BatchNorm (ref :187, ``sync_batch_norm.cc``).

    See the module docstring: under GSPMD sharding the base BatchNorm
    already reduces over the global batch, so this is an alias whose
    ``num_devices`` argument is accepted and ignored.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, **kwargs):
        super().__init__(momentum=momentum, epsilon=epsilon,
                         in_channels=in_channels, **kwargs)
