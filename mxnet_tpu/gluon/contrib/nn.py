"""gluon.contrib.nn — auxiliary layers (parity: gluon/contrib/nn/basic_layers.py).

``SyncBatchNorm`` deserves a note: the reference needs a dedicated
cross-GPU op (``sync_batch_norm.cc``) because each GPU computes batch
stats over its local slice.  Under this framework's GSPMD training
(``parallel.JitTrainStep``), arrays are *logically global* — a plain
BatchNorm's ``mean``/``var`` reduce over the whole sharded batch and
XLA inserts the ICI all-reduce automatically.  SyncBatchNorm is
therefore literally BatchNorm here; the class exists for API parity and
to document the semantics.
"""
from __future__ import annotations

from ..block import HybridBlock
from .. import nn as _nn


class Concurrent(_nn.HybridSequential):
    """Run children on the same input, concat outputs (ref :29)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        outs = [block(x) for block in self._children.values()]
        return F.concat(*outs, dim=self.axis)


class HybridConcurrent(Concurrent):
    """Alias of Concurrent (everything here hybridizes; ref :77)."""


class Identity(HybridBlock):
    """Pass-through block (ref :127) — useful in Concurrent branches."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(_nn.Embedding):
    """Embedding with row_sparse gradients (ref :147).

    Sugar for ``nn.Embedding(..., sparse_grad=True)`` — the gradient is
    a RowSparseNDArray of just the touched rows, applied lazily by the
    optimizer (gather→step→scatter).
    """

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(input_dim, output_dim, dtype=dtype,
                         weight_initializer=weight_initializer,
                         sparse_grad=True, **kwargs)


class SyncBatchNorm(_nn.BatchNorm):
    """Cross-device BatchNorm (ref :187, ``sync_batch_norm.cc``).

    See the module docstring: under GSPMD sharding the base BatchNorm
    already reduces over the global batch, so this is an alias whose
    ``num_devices`` argument is accepted and ignored.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, **kwargs):
        super().__init__(momentum=momentum, epsilon=epsilon,
                         in_channels=in_channels, **kwargs)


class PixelShuffle1D(HybridBlock):
    """Pixel-shuffle upsampling in 1D (parity: contrib/nn
    PixelShuffle1D): (N, C*f, W) -> (N, C, W*f)."""

    def __init__(self, factor):
        super().__init__()
        self._factor = int(factor)

    def hybrid_forward(self, F, x):
        f = self._factor
        n, cf, w = x.shape
        x = F.reshape(x, shape=(n, cf // f, f, w))
        x = F.transpose(x, axes=(0, 1, 3, 2))       # (N, C, W, f)
        return F.reshape(x, shape=(n, cf // f, w * f))

    def __repr__(self):
        return "PixelShuffle1D(%d)" % self._factor


class PixelShuffle2D(HybridBlock):
    """Pixel-shuffle upsampling in 2D (parity: contrib/nn
    PixelShuffle2D): (N, C*f1*f2, H, W) -> (N, C, H*f1, W*f2)."""

    def __init__(self, factor):
        super().__init__()
        try:
            self._factors = (int(factor),) * 2
        except TypeError:
            self._factors = tuple(int(fac) for fac in factor)
            assert len(self._factors) == 2, "wrong length %s" % (factor,)

    def hybrid_forward(self, F, x):
        f1, f2 = self._factors
        n, c, h, w = x.shape
        co = c // (f1 * f2)
        x = F.reshape(x, shape=(n, co, f1, f2, h, w))
        x = F.transpose(x, axes=(0, 1, 4, 2, 5, 3))  # (N,C,H,f1,W,f2)
        return F.reshape(x, shape=(n, co, h * f1, w * f2))

    def __repr__(self):
        return "PixelShuffle2D(%s)" % (self._factors,)


class PixelShuffle3D(HybridBlock):
    """Pixel-shuffle upsampling in 3D (parity: contrib/nn
    PixelShuffle3D): (N, C*f1*f2*f3, D, H, W) ->
    (N, C, D*f1, H*f2, W*f3)."""

    def __init__(self, factor):
        super().__init__()
        try:
            self._factors = (int(factor),) * 3
        except TypeError:
            self._factors = tuple(int(fac) for fac in factor)
            assert len(self._factors) == 3, "wrong length %s" % (factor,)

    def hybrid_forward(self, F, x):
        f1, f2, f3 = self._factors
        n, c, d, h, w = x.shape
        co = c // (f1 * f2 * f3)
        x = F.reshape(x, shape=(n, co, f1, f2, f3, d, h, w))
        x = F.transpose(x, axes=(0, 1, 5, 2, 6, 3, 7, 4))
        return F.reshape(x, shape=(n, co, d * f1, h * f2, w * f3))

    def __repr__(self):
        return "PixelShuffle3D(%s)" % (self._factors,)
