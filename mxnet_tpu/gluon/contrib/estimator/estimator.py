"""Estimator: high-level fit/evaluate loop (parity:
gluon/contrib/estimator/estimator.py:42).

Drives a Gluon net through epochs of a DataLoader with pluggable event
handlers.  The inner step is the ordinary imperative record/backward/step
triple — on TPU the heavy path is already one XLA executable per step via
the hybridized net (hybridize() before fit for the fused path).
"""
from __future__ import annotations

import time as _time

from .... import autograd
from ....base import MXNetError
from ....telemetry import metrics as _metrics
from ....metric import EvalMetric, Loss as LossMetric
from ... import trainer as trainer_mod
from ...loss import Loss
from .event_handler import (
    TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchBegin, BatchEnd,
    StoppingHandler, MetricHandler, LoggingHandler, ValidationHandler,
)


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Estimator:
    """Parity: estimator.py:42 (fit:305, evaluate:199, logger wiring)."""

    def __init__(self, net, loss, metrics=None, trainer=None, context=None,
                 evaluation_loss=None):
        self.net = net
        self.loss = loss
        if not isinstance(loss, Loss):
            raise MXNetError("loss must be a gluon.loss.Loss")
        self.evaluation_loss = evaluation_loss or loss
        self.train_metrics = _as_list(metrics)
        for m in self.train_metrics:
            if not isinstance(m, EvalMetric):
                raise MXNetError("metrics must be EvalMetric instances")
        # mirrored val metrics (fresh instances would need constructor
        # args; reuse types where trivially possible, else share)
        self.val_metrics = [type(m)() if type(m).__init__ is
                            EvalMetric.__init__ else m
                            for m in self.train_metrics]
        self.train_loss_metric = LossMetric(name="loss")
        self.val_loss_metric = LossMetric(name="validation loss")
        self.trainer = trainer
        self.context = context
        self.stop_training = False
        # set by CheckpointHandler(resume_from_checkpoint=True) at
        # train_begin; StoppingHandler budgets remaining epochs from it
        self.resumed_from_epoch = 0

    # ------------------------------------------------------------------
    def _ensure_trainer(self):
        if self.trainer is None:
            self.trainer = trainer_mod.Trainer(
                self.net.collect_params(), "adam",
                {"learning_rate": 1e-3})

    def _batch_fn(self, batch):
        if isinstance(batch, (list, tuple)):
            data, label = batch[0], batch[1]
        else:
            data, label = batch.data[0], batch.label[0]
        return data, label

    def evaluate(self, val_data, batch_fn=None):
        """One pass over val_data updating val metrics (ref :199)."""
        for m in self.val_metrics:
            m.reset()
        self.val_loss_metric.reset()
        for batch in val_data:
            data, label = (batch_fn or self._batch_fn)(batch)
            with autograd.predict_mode():
                pred = self.net(data)
                loss = self.evaluation_loss(pred, label)
            for m in self.val_metrics:
                m.update(label, pred)
            self.val_loss_metric.update(0, loss)
        return [self.val_loss_metric] + self.val_metrics

    def fit(self, train_data, val_data=None, epochs=None,
            event_handlers=None, batches=None, batch_fn=None):
        """Train for ``epochs`` (or ``batches``) with event hooks (ref :305)."""
        if epochs is None and batches is None:
            raise MXNetError("pass epochs or batches")
        self._ensure_trainer()
        handlers = self._prepare_handlers(val_data, event_handlers,
                                          epochs, batches)
        train_begin, epoch_begin, batch_begin, batch_end, epoch_end, \
            train_end = self._categorize(handlers)

        self.stop_training = False
        for h in train_begin:
            h.train_begin(self)
        while not self.stop_training:
            for h in epoch_begin:
                h.epoch_begin(self)
            for batch in train_data:
                data, label = (batch_fn or self._batch_fn)(batch)
                for h in batch_begin:
                    h.batch_begin(self, batch=batch)
                t0 = _time.perf_counter() if _metrics.enabled() else 0.0
                with autograd.record():
                    pred = self.net(data)
                    loss = self.loss(pred, label)
                loss.backward()
                batch_size = data.shape[0]
                self.trainer.step(batch_size)
                if _metrics.enabled():
                    # whole fwd+bwd+step dispatch for one batch —
                    # coarser than mxnet_trainer_step_seconds, which
                    # times only the optimizer step inside it
                    dt = _time.perf_counter() - t0
                    _metrics.histogram(
                        "mxnet_estimator_batch_seconds",
                        help="estimator fwd+bwd+step dispatch per batch"
                    ).observe(dt)
                    if dt > 0:
                        _metrics.gauge(
                            "mxnet_estimator_samples_per_sec",
                            help="batch_size / last batch time"
                        ).set(batch_size / dt)
                self.train_loss_metric.update(0, loss)
                for h in batch_end:
                    if h.batch_end(self, batch=batch, pred=pred,
                                   label=label, loss=loss):
                        self.stop_training = True
                if self.stop_training:
                    break
            for h in epoch_end:
                if h.epoch_end(self):
                    self.stop_training = True
        for h in train_end:
            h.train_end(self)
        return self

    # ------------------------------------------------------------------
    def _prepare_handlers(self, val_data, event_handlers, epochs, batches):
        handlers = _as_list(event_handlers)
        if not any(isinstance(h, StoppingHandler) for h in handlers):
            handlers.append(StoppingHandler(epochs, batches))
        if not any(isinstance(h, MetricHandler) for h in handlers):
            handlers.append(MetricHandler(
                [self.train_loss_metric] + self.train_metrics))
        if val_data is not None and \
                not any(isinstance(h, ValidationHandler) for h in handlers):
            handlers.append(ValidationHandler(val_data, self.evaluate))
        if not any(isinstance(h, LoggingHandler) for h in handlers):
            handlers.append(LoggingHandler(
                metrics=[self.train_loss_metric] + self.train_metrics))
        return handlers

    def _categorize(self, handlers):
        def order(h):
            return getattr(h, "priority", 0)

        cats = []
        for cls in (TrainBegin, EpochBegin, BatchBegin, BatchEnd,
                    EpochEnd, TrainEnd):
            cats.append(sorted((h for h in handlers if isinstance(h, cls)),
                               key=order))
        tb, eb, bb, be, ee, te = cats
        return tb, eb, bb, be, ee, te
