"""Keras-like Estimator facade (parity: gluon/contrib/estimator/)."""
from .estimator import Estimator  # noqa: F401
from .event_handler import (  # noqa: F401
    TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchBegin, BatchEnd,
    StoppingHandler, MetricHandler, ValidationHandler, LoggingHandler,
    CheckpointHandler, EarlyStoppingHandler,
)
