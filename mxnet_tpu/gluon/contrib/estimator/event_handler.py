"""Training event handlers (parity: gluon/contrib/estimator/event_handler.py).

Mixin interfaces (TrainBegin/TrainEnd/EpochBegin/EpochEnd/BatchBegin/
BatchEnd) plus the stock handlers: stopping, metric bookkeeping,
validation scheduling, logging, checkpointing, early stopping.
"""
from __future__ import annotations

import logging
import os
import re
import signal
import threading
import time

import numpy as np


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop after max_epoch epochs or max_batch batches (ref :50)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        # a CheckpointHandler(resume_from_checkpoint=True) runs first
        # (user handlers precede the auto-appended StoppingHandler in
        # the estimator's stable priority sort) and records the epoch
        # it restored — the epoch budget counts from there, not zero
        self.current_epoch = getattr(estimator, "resumed_from_epoch", 0)
        if self.max_epoch and self.current_epoch >= self.max_epoch:
            # already trained to budget: don't run a single extra epoch
            self.stop_training = True
            estimator.stop_training = True

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch >= self.max_batch:
            self.stop_training = True
        return self.stop_training

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch >= self.max_epoch:
            self.stop_training = True
        return self.stop_training


class MetricHandler(EpochBegin, BatchEnd):
    """Reset train metrics each epoch, update per batch (ref :126)."""

    def __init__(self, metrics, priority=-1000):
        self.metrics = metrics
        self.priority = priority

    def epoch_begin(self, estimator, *args, **kwargs):
        for m in self.metrics:
            m.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs.get("pred")
        label = kwargs.get("label")
        loss = kwargs.get("loss")
        for m in self.metrics:
            if getattr(m, "name", "").startswith("loss") or \
                    type(m).__name__ == "Loss":
                if loss is not None:
                    m.update(0, loss)
            elif pred is not None and label is not None:
                m.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Run evaluation every N epochs/batches (ref :182)."""

    def __init__(self, val_data, eval_fn, epoch_period=1, batch_period=None,
                 priority=-1000):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.priority = priority
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and \
                self.current_batch % self.batch_period == 0:
            self.eval_fn(self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and \
                self.current_epoch % self.epoch_period == 0:
            self.eval_fn(self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchEnd):
    """Periodic throughput/metric logging (ref :276, Speedometer-style)."""

    def __init__(self, log_interval="epoch", metrics=None, priority=-3000):
        self.log_interval = log_interval
        self.metrics = metrics or []
        self.priority = priority
        self.batch_index = 0
        self.processed_samples = 0
        self.logger = logging.getLogger("mxnet_tpu.estimator")
        self._tic = None

    def train_begin(self, estimator, *args, **kwargs):
        self._train_tic = time.time()
        self.logger.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        self.logger.info("Training finished in %.1fs",
                         time.time() - self._train_tic)

    def epoch_begin(self, estimator, *args, **kwargs):
        self._tic = time.time()
        self.batch_index = 0
        self.processed_samples = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.batch_index += 1
        batch = kwargs.get("batch")
        if batch is not None:
            try:
                self.processed_samples += batch[0].shape[0]
            except Exception:
                pass
        if isinstance(self.log_interval, int) and \
                self.batch_index % self.log_interval == 0:
            self._log("batch %d" % self.batch_index)

    def epoch_end(self, estimator, *args, **kwargs):
        dt = time.time() - (self._tic or time.time())
        speed = self.processed_samples / dt if dt > 0 else 0.0
        self._log("epoch done: %.1f samples/sec" % speed)

    def _log(self, prefix):
        parts = [prefix]
        for m in self.metrics:
            name, val = m.get()
            parts.append("%s=%s" % (name, val))
        self.logger.info(" ".join(str(p) for p in parts))


class CheckpointHandler(TrainBegin, TrainEnd, BatchEnd, EpochEnd):
    """Save parameters (and trainer states) periodically, keeping the best
    by a monitored metric (ref :392).

    Preemption safety (docs/fault_tolerance.md): every write is atomic
    (``save_parameters``/``save_states`` rename a fully-written temp file
    into place), ``resume_from_checkpoint=True`` restores the latest
    ``<prefix>-epoch<N>.params`` (+ ``.states``) at ``train_begin`` and
    publishes ``estimator.resumed_from_epoch`` so the stopping handler
    budgets the REMAINING epochs, and a SIGTERM received during training
    checkpoints to ``<prefix>-sigterm.params`` before re-raising the
    previous handler — the standard eviction flow on preemptible pods.
    """

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 verbose=0, save_best=False, mode="auto", epoch_period=1,
                 batch_period=None, max_checkpoints=5,
                 resume_from_checkpoint=False):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.max_checkpoints = max_checkpoints
        self.resume_from_checkpoint = resume_from_checkpoint
        self.current_epoch = 0
        self.current_batch = 0
        self.saved = []
        self._prev_sigterm = None
        if mode == "auto" and monitor is not None:
            name = monitor.get()[0] if hasattr(monitor, "get") else ""
            mode = "max" if "acc" in str(name) or "f1" in str(name) \
                else "min"
        self._cmp = (lambda a, b: a > b) if mode == "max" \
            else (lambda a, b: a < b)
        self.best = None

    def train_begin(self, estimator, *args, **kwargs):
        os.makedirs(self.model_dir, exist_ok=True)
        if self.resume_from_checkpoint:
            self._resume(estimator)
        self._install_sigterm(estimator)

    def train_end(self, estimator, *args, **kwargs):
        self._restore_sigterm()

    def _latest_epoch_checkpoint(self):
        """(epoch, path) of the newest ``<prefix>-epoch<N>.params`` in
        ``model_dir``, or (None, None)."""
        pat = re.compile(r"^%s-epoch(\d+)\.params$"
                         % re.escape(self.model_prefix))
        best = (None, None)
        try:
            entries = os.listdir(self.model_dir)
        except OSError:
            return best
        for name in entries:
            m = pat.match(name)
            if m and (best[0] is None or int(m.group(1)) > best[0]):
                best = (int(m.group(1)),
                        os.path.join(self.model_dir, name))
        return best

    def _resume(self, estimator):
        epoch, path = self._latest_epoch_checkpoint()
        if path is None:
            estimator.resumed_from_epoch = 0
            return
        estimator.net.load_parameters(path)
        if estimator.trainer is not None and \
                os.path.exists(path + ".states"):
            try:
                estimator.trainer.load_states(path + ".states")
            except Exception:
                logging.getLogger("mxnet_tpu.estimator").warning(
                    "resume: restored %s but not %s.states", path, path)
        self.current_epoch = epoch
        estimator.resumed_from_epoch = epoch
        logging.getLogger("mxnet_tpu.estimator").info(
            "resumed from checkpoint %s (epoch %d)", path, epoch)

    def _install_sigterm(self, estimator):
        # signal handlers are a main-thread privilege; estimator.fit on
        # a worker thread just skips the SIGTERM hook
        if threading.current_thread() is not threading.main_thread():
            return
        prev = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            self._save(estimator, "sigterm")
            self._restore_sigterm()
            if callable(prev):
                prev(signum, frame)
            else:
                raise SystemExit(128 + signum)

        self._prev_sigterm = prev
        signal.signal(signal.SIGTERM, _on_term)

    def _restore_sigterm(self):
        if self._prev_sigterm is not None and \
                threading.current_thread() is threading.main_thread():
            signal.signal(signal.SIGTERM, self._prev_sigterm)
            self._prev_sigterm = None

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and \
                self.current_batch % self.batch_period == 0:
            self._save(estimator, "batch%d" % self.current_batch)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and \
                self.current_epoch % self.epoch_period == 0:
            self._save(estimator, "epoch%d" % self.current_epoch)
        if self.save_best and self.monitor is not None:
            _, val = self.monitor.get()
            if isinstance(val, (int, float, np.floating)) and \
                    not np.isnan(val):
                if self.best is None or self._cmp(val, self.best):
                    self.best = val
                    path = os.path.join(
                        self.model_dir,
                        "%s-best.params" % self.model_prefix)
                    estimator.net.save_parameters(path)

    def _save(self, estimator, tag):
        path = os.path.join(self.model_dir,
                            "%s-%s.params" % (self.model_prefix, tag))
        estimator.net.save_parameters(path)
        if estimator.trainer is not None:
            try:
                estimator.trainer.save_states(path + ".states")
            except Exception:
                pass
        self.saved.append(path)
        while len(self.saved) > self.max_checkpoints:
            old = self.saved.pop(0)
            for f in (old, old + ".states"):
                if os.path.exists(f):
                    os.remove(f)


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    """Stop when a monitored metric stops improving (ref :625)."""

    def __init__(self, monitor, min_delta=0, patience=0, mode="auto",
                 baseline=None):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.baseline = baseline
        name = monitor.get()[0] if hasattr(monitor, "get") else ""
        if mode == "auto":
            mode = "max" if "acc" in str(name) or "f1" in str(name) \
                else "min"
        self._mode = mode
        self.wait = 0
        self.best = None
        self.stop_training = False
        self.stopped_epoch = 0
        self.current_epoch = 0

    def _improved(self, val):
        if self.best is None:
            return True
        if self._mode == "max":
            return val > self.best + self.min_delta
        return val < self.best - self.min_delta

    def train_begin(self, estimator, *args, **kwargs):
        self.wait = 0
        self.best = self.baseline
        self.stop_training = False

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        _, val = self.monitor.get()
        if not isinstance(val, (int, float, np.floating)) or np.isnan(val):
            return self.stop_training
        if self._improved(val):
            self.best = val
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = self.current_epoch
                self.stop_training = True
        return self.stop_training

    def train_end(self, estimator, *args, **kwargs):
        if self.stopped_epoch:
            logging.getLogger("mxnet_tpu.estimator").info(
                "Early stopping at epoch %d", self.stopped_epoch)
