"""Gluon Trainer.

Reference: ``python/mxnet/gluon/trainer.py`` — applies an Optimizer to a set
of Parameters, routing gradient aggregation through a KVStore
(``_init_kvstore:174``, ``step:320``, ``allreduce_grads:349``).

TPU-native: on a single logical device the optimizer runs as ONE jitted XLA
computation over the whole parameter list with donated buffers — the
reference's multi-tensor fused-optimizer path (``multi_sgd_update``,
``multi_lamb.cc``) generalized to every optimizer.  Multi-device gradient
aggregation is an XLA ``psum`` compiled into the training step by the
``parallel`` package (kvstore='device' semantics over ICI); the explicit
KVStore object remains for API parity and for the dist_* modes.
"""
from __future__ import annotations

import time as _time

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..telemetry import metrics as _metrics
from .. import optimizer as opt_mod
from .parameter import ParameterDict, Parameter


class Trainer:
    """Parity: gluon.Trainer (trainer.py:28)."""

    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError(
                "params should be a list / dict / ParameterDict, got %s"
                % type(params).__name__)
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise MXNetError(
                    "invalid parameter of type %s" % type(param).__name__)
            self._param2idx[param.name] = i
            self._params.append(param)
        self._compression_params = compression_params
        self._kvstore_str = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._states = [None] * len(self._params)
        self._states_created = [False] * len(self._params)
        self._fused_cache = {}

        optimizer_params = optimizer_params or {}
        self._init_optimizer(optimizer, optimizer_params)
        self._scale = self._optimizer.rescale_grad

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt_mod.Optimizer):
            if optimizer_params:
                raise MXNetError(
                    "optimizer_params must be None when optimizer is an "
                    "Optimizer instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt_mod.create(
                optimizer, param_dict=param_dict, **optimizer_params)

    # ------------------------------------------------------------------
    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _init_kvstore(self):
        """Resolve the kvstore string (parity: trainer.py:174)."""
        from ..kvstore import create as kv_create

        if self._kvstore_str is None:
            self._kvstore = None
        elif isinstance(self._kvstore_str, str):
            self._kvstore = kv_create(self._kvstore_str)
        else:
            self._kvstore = self._kvstore_str
        self._kv_initialized = True

    @property
    def kvstore(self):
        if not self._kv_initialized:
            self._init_kvstore()
        return self._kvstore

    # ------------------------------------------------------------------
    def _ensure_states(self):
        for i, param in enumerate(self._params):
            if not self._states_created[i] and param.grad_req != "null":
                self._states[i] = self._optimizer.create_state(
                    i, param.data())
                self._states_created[i] = True

    def _check_and_rescale_grad(self, scale):
        self._optimizer.rescale_grad = scale

    def allreduce_grads(self):
        """Sum gradients across devices (parity: trainer.py:349).

        Single-chip: no-op.  Under SPMD (pjit'd train step built by
        ``mxnet_tpu.parallel``) the psum is compiled into the step itself —
        this method exists for the explicit-kvstore path.
        """
        if not self._kv_initialized:
            self._init_kvstore()
        if self._kvstore is not None and self._kvstore.size > 1:
            for i, param in enumerate(self._params):
                if param.grad_req != "null" and param._data is not None:
                    out = param.grad()
                    self._kvstore.pushpull(i, param.grad(), out=out)
                    # .grad() returns a fresh wrapper; write the aggregated
                    # value back into the parameter's real gradient buffer
                    param._data._grad = out.data()

    def step(self, batch_size, ignore_stale_grad=False):
        """Rescale by 1/batch_size, aggregate, and apply one update.

        Parity: Trainer.step (trainer.py:320).  With a ``dist_*`` kvstore
        the optimizer runs server-side (update_on_kvstore, reference
        trainer.py:174): grads are pushed, updated weights pulled back.
        """
        if not self._kv_initialized:
            self._init_kvstore()
        t0 = _time.perf_counter() if _metrics.enabled() else 0.0
        self._optimizer.rescale_grad = self._scale / batch_size
        kv = self._kvstore
        if kv is not None and str(kv.type).startswith("dist") \
                and self._update_on_kvstore is not False:
            self._dist_step(ignore_stale_grad)
        else:
            self.allreduce_grads()
            self.update(batch_size, ignore_stale_grad, _rescaled=True)
        if _metrics.enabled():
            # dispatch time, not device time: the update is async on
            # the PJRT stream (docs/observability.md)
            dt = _time.perf_counter() - t0
            _metrics.histogram("mxnet_trainer_step_seconds",
                               help="Trainer.step dispatch wall time"
                               ).observe(dt)
            if dt > 0:
                _metrics.gauge("mxnet_trainer_samples_per_sec",
                               help="batch_size / last step time"
                               ).set(batch_size / dt)

    def _dist_step(self, ignore_stale_grad=False):
        """Push grads / pull weights through a distributed kvstore whose
        server runs the optimizer (parity: update_on_kvstore path)."""
        kv = self._kvstore
        if not getattr(self, "_dist_initialized", False):
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    kv.init(i, param.data())
            self._dist_initialized = True
            self._dist_sent_state = None
        # the server holds a pickled COPY of the optimizer: re-send it
        # whenever worker-side mutable knobs change (rescale_grad moves
        # with batch_size; lr with schedulers)
        state = (self._optimizer.rescale_grad, self._optimizer.learning_rate)
        if state != self._dist_sent_state:
            kv.set_optimizer(self._optimizer)
            self._dist_sent_state = state
        for i, param in enumerate(self._params):
            if param.grad_req == "null" or param._data is None:
                continue
            if param._data._grad is None or not param._data._fresh_grad:
                if ignore_stale_grad:
                    continue
                raise MXNetError(
                    "stale gradient for parameter %s" % param.name)
            kv.push(i, param._data.grad)
            out = param.data()
            kv.pull(i, out=out)
            param._data._fresh_grad = False

    def update(self, batch_size, ignore_stale_grad=False, _rescaled=False):
        if not _rescaled:
            self._optimizer.rescale_grad = self._scale / batch_size
        self._ensure_states()
        opt = self._optimizer

        active = []
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if param._data is None:
                if not ignore_stale_grad:
                    raise MXNetError(
                        "parameter %s has not been initialized" % param.name)
                continue
            if param._data._grad is None or not param._data._fresh_grad:
                if ignore_stale_grad:
                    continue
                raise MXNetError(
                    "gradient of parameter %s has not been updated by "
                    "backward() since the last step; this could mean a bug "
                    "in your model that made it only use a subset of the "
                    "parameters for this iteration; pass "
                    "ignore_stale_grad=True to suppress"
                    % param.name)
            active.append(i)
        if not active:
            return

        # row-sparse grads take the lazy per-parameter scatter path
        from ..ndarray.sparse import BaseSparseNDArray

        sparse_active = [i for i in active
                         if isinstance(self._params[i]._data._grad,
                                       BaseSparseNDArray)]
        if sparse_active:
            active = [i for i in active if i not in set(sparse_active)]
            for i in sparse_active:
                param = self._params[i]
                opt._update_count(i)
                lr = opt._get_lr(i)
                wd = opt._get_wd(i)
                t = opt._index_update_count[i]
                rsp = param._data._grad.compact()
                w = param.data().data()
                dev = list(w.devices())[0] if hasattr(w, "devices") else None
                idx = rsp.indices.data().astype(jnp.int32)
                vals = rsp.values.data().astype(w.dtype)
                if dev is not None:
                    # grads' index arrays may be committed to the host
                    # context; the update must run where the weight lives
                    idx = jax.device_put(idx, dev)
                    vals = jax.device_put(vals, dev)
                new_w, new_s = opt._get_sparse_jit_step()(
                    w, self._states[i], vals, idx,
                    jnp.float32(lr), jnp.float32(wd), jnp.int32(t))
                param._data._set_data(new_w)
                param._data._fresh_grad = False
                self._states[i] = new_s
        if not active:
            return

        # one fused XLA update over all parameters (multi-tensor path)
        key = (tuple(active), float(opt.rescale_grad))
        fused = self._fused_cache.get(key)
        if fused is None:
            def fused_fn(weights, grads, states, lrs, wds, ts):
                new_w, new_s = [], []
                for w, g, s, lr, wd, t in zip(weights, grads, states, lrs,
                                              wds, ts):
                    nw, ns = opt._step(w, g, s, lr, wd, t)
                    new_w.append(nw)
                    new_s.append(ns)
                return new_w, new_s

            fused = jax.jit(fused_fn, donate_argnums=(0, 2))
            self._fused_cache[key] = fused

        weights, grads, states, lrs, wds, ts = [], [], [], [], [], []
        for i in active:
            param = self._params[i]
            opt._update_count(i)
            weights.append(param.data().data())
            grads.append(param._data._grad)
            states.append(self._states[i])
            lrs.append(jnp.float32(opt._get_lr(i)))
            wds.append(jnp.float32(opt._get_wd(i)))
            ts.append(jnp.int32(opt._index_update_count[i]))

        # the fused call donates weight/state buffers; a pending bulk
        # segment may still hold an old weight as input (e.g. a recorded
        # forward whose output was never read) — drain it first or its
        # flush would read a deleted array (engine.flush_if_referencing)
        from ..engine import Engine

        Engine.get().flush_if_referencing(
            weights + jax.tree_util.tree_leaves(states), "trainer_step")
        new_weights, new_states = fused(weights, grads, states, lrs, wds, ts)
        for i, nw, ns in zip(active, new_weights, new_states):
            self._params[i]._data._set_data(nw)
            self._params[i]._data._fresh_grad = False
            self._states[i] = ns

    # ------------------------------------------------------------------
    def save_states(self, fname):
        """Parity: Trainer.save_states — written as a checksummed MXGC1
        global checkpoint (sharding/checkpoint.py): every optimizer-state
        leaf stored once with a per-entry crc32, atomically, so a torn or
        bit-flipped file is DETECTED at load (named entry) instead of
        surfacing a raw unpickling error."""
        from .. import sharding as _shd

        assert self._optimizer is not None
        self._ensure_states()

        def entries():
            for i, st in enumerate(self._states):
                if st is None:
                    continue
                for j, leaf in enumerate(jax.tree_util.tree_leaves(st)):
                    yield "state/%d/%d" % (i, j), jax.device_get(leaf), \
                        None
        meta = {
            "kind": "trainer",
            "num_update": int(self._optimizer.num_update),
            "index_update_count": {
                str(k): int(v) for k, v in
                self._optimizer._index_update_count.items()},
        }
        # atomic (inside save_global): a preemption mid-dump must not
        # corrupt the previous states file (docs/fault_tolerance.md)
        _shd.save_global(fname, entries(), meta=meta)

    def load_states(self, fname):
        from ..base import MXNetError
        from .. import sharding as _shd

        if _shd.is_global_checkpoint(fname):
            # live treedefs rebuild the trees from flat leaves — the
            # format stores arrays + names only, never code
            self._ensure_states()
            entries, meta = _shd.load_global(fname)
            states = []
            for i, st in enumerate(self._states):
                if st is None:
                    states.append(None)
                    continue
                treedef = jax.tree_util.tree_structure(st)
                leaves = []
                for j in range(treedef.num_leaves):
                    name = "state/%d/%d" % (i, j)
                    ent = entries.get(name)
                    if ent is None:
                        raise MXNetError(
                            "trainer checkpoint %s: missing entry %r "
                            "(optimizer config changed?)" % (fname, name))
                    leaves.append(jnp.asarray(ent["array"]))
                states.append(jax.tree_util.tree_unflatten(treedef,
                                                           leaves))
            self._states = states
            self._optimizer.num_update = int(meta["num_update"])
            self._optimizer._index_update_count = {
                int(k): int(v)
                for k, v in meta["index_update_count"].items()}
        else:
            import pickle

            try:
                with open(fname, "rb") as f:
                    payload = pickle.load(f)
            except Exception as e:  # noqa: BLE001 — torn legacy pickle
                raise MXNetError(
                    "trainer checkpoint %s is neither MXGC1 nor a "
                    "loadable legacy pickle (%s: %s) — corrupt or "
                    "truncated" % (fname, type(e).__name__, e))
            self._states = [
                jax.tree_util.tree_map(jnp.asarray, s)
                if s is not None else None
                for s in payload["states"]]
            self._optimizer.num_update = payload["num_update"]
            self._optimizer._index_update_count = \
                payload["index_update_count"]
        self._states_created = [True] * len(self._states)
