"""``mx.np.random`` (parity: python/mxnet/numpy/random.py).

Draws come from the framework's global threefry key chain
(``mxnet_tpu.random``) — same stateless-PRNG discipline as ``mx.nd.random``,
so ``mx.random.seed`` reproduces np-frontend draws too.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from . import ndarray, array, _as_np
from .. import random as _random
from ..ndarray.ndarray import NDArray, _to_jax_dtype


def _key():
    return _random.next_key()


def _shape(size):
    if size is None:
        return ()
    if isinstance(size, int):
        return (size,)
    return tuple(size)


def uniform(low=0.0, high=1.0, size=None, dtype=None, ctx=None):
    dt = _to_jax_dtype(dtype) if dtype else jnp.float32
    return ndarray(jax.random.uniform(_key(), _shape(size), dt,
                                      minval=low, maxval=high), ctx=ctx)


def normal(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None):
    dt = _to_jax_dtype(dtype) if dtype else jnp.float32
    return ndarray(jax.random.normal(_key(), _shape(size), dt)
                   * scale + loc, ctx=ctx)


def randn(*size):
    return normal(size=size or None)


def rand(*size):
    return uniform(size=size or None)


def randint(low, high=None, size=None, dtype="int32", ctx=None):
    if high is None:
        low, high = 0, low
    return ndarray(jax.random.randint(_key(), _shape(size), low, high,
                                      _to_jax_dtype(dtype)), ctx=ctx)


def choice(a, size=None, replace=True, p=None, ctx=None):
    if isinstance(a, NDArray):
        pool = a.data()
    elif isinstance(a, int):
        pool = jnp.arange(a)
    else:
        pool = jnp.asarray(a)
    probs = None
    if p is not None:
        probs = p.data() if isinstance(p, NDArray) else jnp.asarray(p)
    return ndarray(jax.random.choice(_key(), pool, _shape(size),
                                     replace=replace, p=probs), ctx=ctx)


def shuffle(x):
    """In-place permutation along the first axis (numpy semantics)."""
    perm = jax.random.permutation(_key(), x.shape[0])
    x._set_data(x.data()[perm])


def permutation(x):
    if isinstance(x, int):
        return ndarray(jax.random.permutation(_key(), x))
    raw = x.data() if isinstance(x, NDArray) else jnp.asarray(x)
    return ndarray(jax.random.permutation(_key(), raw))


def beta(a, b, size=None, ctx=None):
    return ndarray(jax.random.beta(_key(), a, b, _shape(size)), ctx=ctx)


def gamma(shape, scale=1.0, size=None, ctx=None):
    return ndarray(jax.random.gamma(_key(), shape, _shape(size)) * scale,
                   ctx=ctx)


def exponential(scale=1.0, size=None, ctx=None):
    return ndarray(jax.random.exponential(_key(), _shape(size)) * scale,
                   ctx=ctx)


def poisson(lam=1.0, size=None, ctx=None):
    return ndarray(jax.random.poisson(_key(), lam, _shape(size)), ctx=ctx)


def multinomial(n, pvals, size=None):
    draws = jax.random.categorical(
        _key(), jnp.log(jnp.asarray(pvals)),
        shape=_shape(size) + (n,) if size else (n,))
    k = len(pvals) if not hasattr(pvals, "shape") else pvals.shape[-1]
    counts = jax.nn.one_hot(draws, k).sum(axis=-2)
    return ndarray(counts.astype(jnp.int64))


def seed(s):
    _random.seed(s)
