"""``mx.np`` — NumPy-compatible frontend.

Capability parity with the reference's numpy frontend
(``python/mxnet/numpy/multiarray.py`` + ``numpy_dispatch_protocol.py``,
~10k LoC): a NumPy-semantics ``ndarray`` (zero-dim and zero-size shapes,
bool comparison results, true division, boolean-mask indexing), the
function namespace, ``np.linalg`` / ``np.random`` submodules, and the
``__array_ufunc__`` / ``__array_function__`` interop protocols.

TPU-native mechanism: no second operator stack.  ``ndarray`` subclasses
the core ``NDArray`` (same XLA buffer, same tape), registry ops propagate
the frontend class through ``_op_result_cls``, and numpy-only functions
lower through ``registry.invoke_fn`` — an ad-hoc traced jnp closure with
full autograd integration.  Zero-dim/zero-size shapes need no ``set_np``
switch here (XLA handles them natively); ``npx.set_np`` is kept as a
compatibility toggle (numpy_extension/__init__.py).
"""
from __future__ import annotations

import builtins

import numpy as _onp
import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..context import current_context
from ..ndarray.ndarray import NDArray, _to_jax_dtype
from ..ops import registry as _reg

__all__ = ["ndarray", "array", "zeros", "ones", "empty", "full", "arange",
           "linspace", "logspace", "eye", "identity", "meshgrid"]

pi = _onp.pi
e = _onp.e
inf = _onp.inf
nan = _onp.nan
newaxis = None
float32 = _onp.float32
float64 = _onp.float64
float16 = _onp.float16
int8 = _onp.int8
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
bool_ = _onp.bool_


def _invoke(fn, tensors, op_name):
    """Trace a jnp closure over ndarray inputs with tape integration."""
    ins = [x if isinstance(x, NDArray) else ndarray(x) for x in tensors]
    (out,) = _reg.invoke_fn(lambda *raw: (fn(*raw),), ins, op_name=op_name)
    return out if isinstance(out, ndarray) else _as_np(out)


def _as_np(x):
    """Rewrap an NDArray as mx.np.ndarray sharing buffer + tape node."""
    if isinstance(x, ndarray):
        return x
    out = ndarray.__new__(ndarray)
    for slot in NDArray.__slots__:
        if slot == "__weakref__":
            continue
        object.__setattr__(out, slot, getattr(x, slot))
    return out


class ndarray(NDArray):
    """NumPy-semantics tensor sharing the core NDArray machinery."""

    __slots__ = ()

    # comparisons return bool arrays (classic mx.nd returns float)
    def _cmp(self, other, jfn):
        if isinstance(other, NDArray):
            return _invoke(lambda a, b: jfn(a, b), [self, other], "_np_cmp")
        return _invoke(lambda a: jfn(a, other), [self], "_np_cmp")

    def __eq__(self, o):
        return self._cmp(o, jnp.equal)

    def __ne__(self, o):
        return self._cmp(o, jnp.not_equal)

    def __gt__(self, o):
        return self._cmp(o, jnp.greater)

    def __ge__(self, o):
        return self._cmp(o, jnp.greater_equal)

    def __lt__(self, o):
        return self._cmp(o, jnp.less)

    def __le__(self, o):
        return self._cmp(o, jnp.less_equal)

    __hash__ = None

    def __matmul__(self, o):
        return matmul(self, o)

    def __mod__(self, o):
        return mod(self, o)

    def __abs__(self):
        return abs(self)

    def __repr__(self):
        return "array(%s)" % _onp.array2string(self.asnumpy(),
                                               separator=", ")

    # numpy protocol interop -------------------------------------------------
    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        if method != "__call__" or kwargs.get("out") is not None:
            return NotImplemented
        fn = globals().get(ufunc.__name__)
        if fn is None:
            return NotImplemented
        return fn(*inputs)

    def __array_function__(self, func, types, args, kwargs):
        fn = globals().get(func.__name__)
        if fn is None:
            return NotImplemented
        return fn(*args, **kwargs)

    # ndarray methods --------------------------------------------------------
    @property
    def T(self):
        return transpose(self)

    def item(self, *args):
        return self.asnumpy().item(*args)

    def tolist(self):
        return self.asnumpy().tolist()

    def astype(self, dtype, copy=True):
        return _invoke(lambda a: a.astype(_to_jax_dtype(dtype)), [self],
                       "_np_astype")

    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        order = kwargs.get("order", "C")
        if order != "C":
            raise MXNetError("only C-order reshape is supported")
        return _invoke(lambda a: a.reshape(shape), [self], "_np_reshape")

    def flatten(self, order="C"):
        return self.reshape((-1,))

    def ravel(self):
        return self.reshape((-1,))

    def squeeze(self, axis=None):
        return squeeze(self, axis)

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return transpose(self, axes or None)

    def swapaxes(self, a1, a2):
        return swapaxes(self, a1, a2)

    def sum(self, axis=None, dtype=None, keepdims=False):
        return sum(self, axis=axis, dtype=dtype, keepdims=keepdims)

    def mean(self, axis=None, dtype=None, keepdims=False):
        return mean(self, axis=axis, dtype=dtype, keepdims=keepdims)

    def max(self, axis=None, keepdims=False):
        return max(self, axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        return min(self, axis=axis, keepdims=keepdims)

    def prod(self, axis=None, keepdims=False):
        return prod(self, axis=axis, keepdims=keepdims)

    def std(self, axis=None, ddof=0, keepdims=False):
        return std(self, axis=axis, ddof=ddof, keepdims=keepdims)

    def var(self, axis=None, ddof=0, keepdims=False):
        return var(self, axis=axis, ddof=ddof, keepdims=keepdims)

    def argmax(self, axis=None):
        return argmax(self, axis=axis)

    def argmin(self, axis=None):
        return argmin(self, axis=axis)

    def cumsum(self, axis=None):
        return cumsum(self, axis=axis)

    def clip(self, a_min=None, a_max=None):
        return clip(self, a_min, a_max)

    def round(self, decimals=0):
        return round(self, decimals)

    def repeat(self, repeats, axis=None):
        return repeat(self, repeats, axis=axis)

    def dot(self, other):
        return dot(self, other)

    def copy(self):
        return _invoke(lambda a: a + 0, [self], "_np_copy")

    def as_nd_ndarray(self):
        """View as a classic mx.nd NDArray (shared buffer)."""
        out = NDArray.__new__(NDArray)
        for slot in NDArray.__slots__:
            if slot == "__weakref__":
                continue
            object.__setattr__(out, slot, getattr(self, slot))
        return out

    def as_np_ndarray(self):
        return self


ndarray._op_result_cls = ndarray


# ---------------------------------------------------------------------------
# creation
# ---------------------------------------------------------------------------

def array(obj, dtype=None, ctx=None):
    if isinstance(obj, NDArray):
        data = obj.data()
        if dtype is not None:
            data = data.astype(_to_jax_dtype(dtype))
        return _as_np(NDArray(data, ctx=ctx))
    a = _onp.asarray(obj, dtype=dtype)
    if a.dtype == _onp.float64 and dtype is None:
        a = a.astype(_onp.float32)
    return ndarray(a, ctx=ctx)


def zeros(shape, dtype="float32", ctx=None):
    return ndarray(jnp.zeros(shape, _to_jax_dtype(dtype)), ctx=ctx)


def ones(shape, dtype="float32", ctx=None):
    return ndarray(jnp.ones(shape, _to_jax_dtype(dtype)), ctx=ctx)


def empty(shape, dtype="float32", ctx=None):
    return zeros(shape, dtype, ctx)


def full(shape, fill_value, dtype=None, ctx=None):
    return ndarray(jnp.full(shape, fill_value,
                            _to_jax_dtype(dtype) if dtype else None),
                   ctx=ctx)


def zeros_like(a, dtype=None):
    return _invoke(lambda x: jnp.zeros_like(
        x, _to_jax_dtype(dtype) if dtype else None), [a], "_np_zeros_like")


def ones_like(a, dtype=None):
    return _invoke(lambda x: jnp.ones_like(
        x, _to_jax_dtype(dtype) if dtype else None), [a], "_np_ones_like")


def full_like(a, fill_value, dtype=None):
    return _invoke(lambda x: jnp.full_like(
        x, fill_value, _to_jax_dtype(dtype) if dtype else None), [a],
        "_np_full_like")


def arange(start, stop=None, step=1, dtype=None, ctx=None):
    dt = _to_jax_dtype(dtype) if dtype else jnp.float32
    return ndarray(jnp.arange(start, stop, step, dt), ctx=ctx)


def linspace(start, stop, num=50, endpoint=True, dtype=None, ctx=None):
    dt = _to_jax_dtype(dtype) if dtype else jnp.float32
    return ndarray(jnp.linspace(start, stop, num, endpoint=endpoint,
                                dtype=dt), ctx=ctx)


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             ctx=None):
    dt = _to_jax_dtype(dtype) if dtype else jnp.float32
    return ndarray(jnp.logspace(start, stop, num, endpoint=endpoint,
                                base=base, dtype=dt), ctx=ctx)


def eye(N, M=None, k=0, dtype="float32", ctx=None):
    return ndarray(jnp.eye(N, M, k, dtype=_to_jax_dtype(dtype)), ctx=ctx)


def identity(n, dtype="float32", ctx=None):
    return eye(n, dtype=dtype, ctx=ctx)


def meshgrid(*xi, indexing="xy"):
    raws = [x.data() if isinstance(x, NDArray) else jnp.asarray(x)
            for x in xi]
    return [ndarray(g) for g in jnp.meshgrid(*raws, indexing=indexing)]


# ---------------------------------------------------------------------------
# elementwise math — generated from a jnp table through invoke_fn
# ---------------------------------------------------------------------------

_UNARY = [
    "negative", "absolute", "sign", "exp", "expm1", "log", "log2", "log10",
    "log1p", "sqrt", "cbrt", "square", "reciprocal", "sin", "cos", "tan",
    "arcsin", "arccos", "arctan", "sinh", "cosh", "tanh", "arcsinh",
    "arccosh", "arctanh", "degrees", "radians", "floor", "ceil", "trunc",
    "rint", "isnan", "isinf", "isfinite", "logical_not", "sort",
]
_BINARY = [
    "add", "subtract", "multiply", "divide", "true_divide", "mod",
    "remainder", "power", "maximum", "minimum", "hypot", "arctan2",
    "logical_and", "logical_or", "logical_xor", "equal", "not_equal",
    "greater", "greater_equal", "less", "less_equal", "fmax", "fmin",
    "floor_divide", "copysign", "logaddexp",
]


def _make_unary(name):
    jfn = getattr(jnp, name)

    def f(x, out=None, **kwargs):
        if not isinstance(x, NDArray):
            x = array(x)
        res = _invoke(lambda a: jfn(a, **kwargs), [x], "_np_" + name)
        if out is not None:
            out._adopt(res)
            return out
        return res

    f.__name__ = name
    return f


def _make_binary(name):
    jfn = getattr(jnp, name)

    def f(x1, x2, out=None):
        t1, t2 = isinstance(x1, NDArray), isinstance(x2, NDArray)
        if t1 and t2:
            res = _invoke(jfn, [x1, x2], "_np_" + name)
        elif t1:
            res = _invoke(lambda a: jfn(a, x2), [x1], "_np_" + name)
        elif t2:
            res = _invoke(lambda b: jfn(x1, b), [x2], "_np_" + name)
        else:
            return array(jfn(jnp.asarray(x1), jnp.asarray(x2)))
        if out is not None:
            out._adopt(res)
            return out
        return res

    f.__name__ = name
    return f


for _n in _UNARY:
    globals()[_n] = _make_unary(_n)
for _n in _BINARY:
    globals()[_n] = _make_binary(_n)

abs = globals()["absolute"]  # noqa: A001
fix = globals()["trunc"]  # np.fix == round toward zero


def sigmoid(x):
    return _invoke(jax.nn.sigmoid, [x], "_np_sigmoid")


def relu(x):
    return _invoke(jax.nn.relu, [x], "_np_relu")


def clip(a, a_min=None, a_max=None, out=None):
    res = _invoke(lambda x: jnp.clip(x, a_min, a_max), [a], "_np_clip")
    if out is not None:
        out._adopt(res)
        return out
    return res


def round(a, decimals=0):  # noqa: A001
    return _invoke(lambda x: jnp.round(x, decimals), [a], "_np_round")


def where(condition, x=None, y=None):
    if x is None and y is None:
        return nonzero(condition)
    return _invoke(lambda c, a, b: jnp.where(c, a, b),
                   [condition, x if isinstance(x, NDArray) else array(x),
                    y if isinstance(y, NDArray) else array(y)], "_np_where")


def nonzero(a):
    raw = a.asnumpy()
    return tuple(ndarray(i.astype(_onp.int64)) for i in _onp.nonzero(raw))


def maximum_(x1, x2):
    return globals()["maximum"](x1, x2)


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------

def _norm_axis(axis):
    if isinstance(axis, list):
        return tuple(axis)
    return axis


def _make_reduce(name, jfn, has_dtype=True):
    def f(a, axis=None, dtype=None, keepdims=False, out=None, **kwargs):
        if not isinstance(a, NDArray):
            a = array(a)
        kw = dict(kwargs)
        if has_dtype and dtype is not None:
            kw["dtype"] = _to_jax_dtype(dtype)
        res = _invoke(lambda x: jfn(x, axis=_norm_axis(axis),
                                    keepdims=keepdims, **kw), [a],
                      "_np_" + name)
        if out is not None:
            out._adopt(res)
            return out
        return res

    f.__name__ = name
    return f


sum = _make_reduce("sum", jnp.sum)  # noqa: A001
mean = _make_reduce("mean", jnp.mean)
prod = _make_reduce("prod", jnp.prod)
max = _make_reduce("max", jnp.max, has_dtype=False)  # noqa: A001
min = _make_reduce("min", jnp.min, has_dtype=False)  # noqa: A001
amax, amin = max, min
nansum = _make_reduce("nansum", jnp.nansum)
nanprod = _make_reduce("nanprod", jnp.nanprod)
all = _make_reduce("all", jnp.all, has_dtype=False)  # noqa: A001
any = _make_reduce("any", jnp.any, has_dtype=False)  # noqa: A001


def std(a, axis=None, dtype=None, ddof=0, keepdims=False):
    return _invoke(lambda x: jnp.std(x, axis=_norm_axis(axis), ddof=ddof,
                                     keepdims=keepdims), [a], "_np_std")


def var(a, axis=None, dtype=None, ddof=0, keepdims=False):
    return _invoke(lambda x: jnp.var(x, axis=_norm_axis(axis), ddof=ddof,
                                     keepdims=keepdims), [a], "_np_var")


def argmax(a, axis=None, out=None):
    return _invoke(lambda x: jnp.argmax(x, axis=axis), [a], "_np_argmax")


def argmin(a, axis=None, out=None):
    return _invoke(lambda x: jnp.argmin(x, axis=axis), [a], "_np_argmin")


def argsort(a, axis=-1):
    return _invoke(lambda x: jnp.argsort(x, axis=axis), [a], "_np_argsort")


def cumsum(a, axis=None, dtype=None):
    return _invoke(lambda x: jnp.cumsum(x, axis=axis), [a], "_np_cumsum")


def average(a, axis=None, weights=None):
    if weights is None:
        return mean(a, axis=axis)
    return _invoke(lambda x, w: jnp.average(x, axis=axis, weights=w),
                   [a, weights], "_np_average")


def median(a, axis=None, keepdims=False):
    return _invoke(lambda x: jnp.median(x, axis=axis, keepdims=keepdims),
                   [a], "_np_median")


# ---------------------------------------------------------------------------
# shape manipulation
# ---------------------------------------------------------------------------

def reshape(a, newshape, order="C"):
    return a.reshape(newshape) if isinstance(a, ndarray) \
        else array(a).reshape(newshape)


def transpose(a, axes=None):
    return _invoke(lambda x: jnp.transpose(x, axes), [a], "_np_transpose")


def swapaxes(a, axis1, axis2):
    return _invoke(lambda x: jnp.swapaxes(x, axis1, axis2), [a],
                   "_np_swapaxes")


def moveaxis(a, source, destination):
    return _invoke(lambda x: jnp.moveaxis(x, source, destination), [a],
                   "_np_moveaxis")


def expand_dims(a, axis):
    return _invoke(lambda x: jnp.expand_dims(x, axis), [a],
                   "_np_expand_dims")


def squeeze(a, axis=None):
    return _invoke(lambda x: jnp.squeeze(x, axis), [a], "_np_squeeze")


def broadcast_to(a, shape):
    return _invoke(lambda x: jnp.broadcast_to(x, shape), [a],
                   "_np_broadcast_to")


def concatenate(seq, axis=0, out=None):
    res = _invoke(lambda *xs: jnp.concatenate(xs, axis=axis), list(seq),
                  "_np_concatenate")
    if out is not None:
        out._adopt(res)
        return out
    return res


def stack(arrays, axis=0, out=None):
    res = _invoke(lambda *xs: jnp.stack(xs, axis=axis), list(arrays),
                  "_np_stack")
    if out is not None:
        out._adopt(res)
        return out
    return res


def vstack(tup):
    return _invoke(lambda *xs: jnp.vstack(xs), list(tup), "_np_vstack")


def hstack(tup):
    return _invoke(lambda *xs: jnp.hstack(xs), list(tup), "_np_hstack")


def dstack(tup):
    return _invoke(lambda *xs: jnp.dstack(xs), list(tup), "_np_dstack")


def split(ary, indices_or_sections, axis=0):
    outs = _reg.invoke_fn(
        lambda x: tuple(jnp.split(x, indices_or_sections, axis=axis)),
        [ary if isinstance(ary, NDArray) else array(ary)],
        op_name="_np_split")
    return [_as_np(o) for o in outs]


def array_split(ary, indices_or_sections, axis=0):
    outs = _reg.invoke_fn(
        lambda x: tuple(jnp.array_split(x, indices_or_sections, axis=axis)),
        [ary if isinstance(ary, NDArray) else array(ary)],
        op_name="_np_array_split")
    return [_as_np(o) for o in outs]


def tile(a, reps):
    return _invoke(lambda x: jnp.tile(x, reps), [a], "_np_tile")


def repeat(a, repeats, axis=None):
    return _invoke(lambda x: jnp.repeat(x, repeats, axis=axis), [a],
                   "_np_repeat")


def flip(a, axis=None):
    return _invoke(lambda x: jnp.flip(x, axis), [a], "_np_flip")


def roll(a, shift, axis=None):
    return _invoke(lambda x: jnp.roll(x, shift, axis), [a], "_np_roll")


def rot90(a, k=1, axes=(0, 1)):
    return _invoke(lambda x: jnp.rot90(x, k, axes), [a], "_np_rot90")


def atleast_1d(*arys):
    outs = [_invoke(jnp.atleast_1d, [a], "_np_atleast_1d") for a in arys]
    return outs[0] if len(outs) == 1 else outs


def pad(a, pad_width, mode="constant", constant_values=0):
    def f(x):
        if mode == "constant":
            return jnp.pad(x, pad_width, mode=mode,
                           constant_values=constant_values)
        return jnp.pad(x, pad_width, mode=mode)
    return _invoke(f, [a], "_np_pad")


def diag(v, k=0):
    return _invoke(lambda x: jnp.diag(x, k), [v], "_np_diag")


def tril(m, k=0):
    return _invoke(lambda x: jnp.tril(x, k), [m], "_np_tril")


def triu(m, k=0):
    return _invoke(lambda x: jnp.triu(x, k), [m], "_np_triu")


def unique(ar, return_index=False, return_inverse=False,
           return_counts=False, axis=None):
    # dynamic output shape → eager host computation (documented deviation)
    res = _onp.unique(ar.asnumpy() if isinstance(ar, NDArray) else ar,
                      return_index=return_index,
                      return_inverse=return_inverse,
                      return_counts=return_counts, axis=axis)
    if isinstance(res, tuple):
        return tuple(ndarray(r) for r in res)
    return ndarray(res)


# ---------------------------------------------------------------------------
# linear algebra at top level
# ---------------------------------------------------------------------------

def dot(a, b, out=None):
    res = _invoke(jnp.dot, [a, b], "_np_dot")
    if out is not None:
        out._adopt(res)
        return out
    return res


def matmul(a, b):
    return _invoke(jnp.matmul, [a, b], "_np_matmul")


def tensordot(a, b, axes=2):
    return _invoke(lambda x, y: jnp.tensordot(x, y, axes=axes), [a, b],
                   "_np_tensordot")


def inner(a, b):
    return _invoke(jnp.inner, [a, b], "_np_inner")


def outer(a, b):
    return _invoke(jnp.outer, [a, b], "_np_outer")


def einsum(subscripts, *operands):
    return _invoke(lambda *xs: jnp.einsum(subscripts, *xs),
                   list(operands), "_np_einsum")


def trace(a, offset=0, axis1=0, axis2=1):
    return _invoke(lambda x: jnp.trace(x, offset, axis1, axis2), [a],
                   "_np_trace")


def kron(a, b):
    return _invoke(jnp.kron, [a, b], "_np_kron")


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------

def shape(a):
    return a.shape if isinstance(a, NDArray) else _onp.shape(a)


def ndim(a):
    return a.ndim if isinstance(a, NDArray) else _onp.ndim(a)


def size(a):
    return a.size if isinstance(a, NDArray) else _onp.size(a)


def may_share_memory(a, b):
    return False


def array_equal(a1, a2):
    a = a1.asnumpy() if isinstance(a1, NDArray) else _onp.asarray(a1)
    b = a2.asnumpy() if isinstance(a2, NDArray) else _onp.asarray(a2)
    return builtins.bool(_onp.array_equal(a, b))


def allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    a = a.asnumpy() if isinstance(a, NDArray) else _onp.asarray(a)
    b = b.asnumpy() if isinstance(b, NDArray) else _onp.asarray(b)
    return builtins.bool(_onp.allclose(a, b, rtol, atol, equal_nan))


def isclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    return _invoke(lambda x, y: jnp.isclose(x, y, rtol, atol, equal_nan),
                   [a if isinstance(a, NDArray) else array(a),
                    b if isinstance(b, NDArray) else array(b)],
                   "_np_isclose")


def one_hot(indices, depth, dtype="float32"):
    return _invoke(lambda i: jax.nn.one_hot(
        i.astype(jnp.int32), depth, dtype=_to_jax_dtype(dtype)),
        [indices], "_np_one_hot")


def searchsorted(a, v, side="left"):
    return _invoke(lambda x, q: jnp.searchsorted(x, q, side=side),
                   [a, v], "_np_searchsorted")


def bincount(x, weights=None, minlength=0):
    # length depends on the data → eager host computation
    xv = x.asnumpy() if isinstance(x, NDArray) else _onp.asarray(x)
    wv = weights.asnumpy() if isinstance(weights, NDArray) else weights
    return ndarray(_onp.bincount(xv.astype(_onp.int64), wv, minlength))


def interp(x, xp, fp):
    return _invoke(lambda a, b, c: jnp.interp(a, b, c), [x, xp, fp],
                   "_np_interp")


def diff(a, n=1, axis=-1):
    return _invoke(lambda x: jnp.diff(x, n=n, axis=axis), [a], "_np_diff")


def cross(a, b, axis=-1):
    return _invoke(lambda x, y: jnp.cross(x, y, axis=axis), [a, b],
                   "_np_cross")


def cumprod(a, axis=None):
    return _invoke(lambda x: jnp.cumprod(x, axis=axis), [a], "_np_cumprod")


def gradient(f, *varargs, axis=None):
    def fn(x):
        g = jnp.gradient(x, *varargs, axis=axis)
        return tuple(g) if isinstance(g, (list, tuple)) else (g,)

    outs = _reg.invoke_fn(
        fn, [f if isinstance(f, NDArray) else array(f)],
        op_name="_np_gradient")
    outs = [_as_np(o) for o in outs]
    return outs[0] if len(outs) == 1 else tuple(outs)


def take(a, indices, axis=None, mode="clip"):
    if isinstance(indices, NDArray):
        return _invoke(lambda x, i: jnp.take(x, i.astype(jnp.int32),
                                             axis=axis, mode=mode),
                       [a, indices], "_np_take")
    return _invoke(lambda x: jnp.take(x, jnp.asarray(indices), axis=axis,
                                      mode=mode), [a], "_np_take")




# ---------------------------------------------------------------------------
# statistics / set / window wave (reference: numpy/multiarray.py +
# src/operator/numpy/np_percentile_op.cc, np_window_op.cc, set ops)
# ---------------------------------------------------------------------------


def percentile(a, q, axis=None, interpolation="linear", keepdims=False):
    if not isinstance(a, NDArray):
        a = array(a)
    return _invoke(lambda x: jnp.percentile(
        x.astype(jnp.float32), jnp.asarray(q, jnp.float32),
        axis=_norm_axis(axis), method=interpolation,
        keepdims=keepdims), [a], "_npi_percentile")


def quantile(a, q, axis=None, interpolation="linear", keepdims=False):
    if not isinstance(a, NDArray):
        a = array(a)
    return _invoke(lambda x: jnp.quantile(
        x.astype(jnp.float32), jnp.asarray(q, jnp.float32),
        axis=_norm_axis(axis), method=interpolation,
        keepdims=keepdims), [a], "_npi_quantile")


def histogram(a, bins=10, range=None):  # noqa: A002
    if isinstance(bins, NDArray) or isinstance(bins, (list, tuple)):
        edges = jnp.asarray(bins.data() if isinstance(bins, NDArray)
                            else bins, jnp.float32)
        counts, e = jnp.histogram(jnp.asarray(_flat(a), jnp.float32),
                                  bins=edges)
        return array(counts), array(e)
    counts, e = jnp.histogram(jnp.asarray(_flat(a), jnp.float32),
                              bins=int(bins), range=range)
    return array(counts), array(e)


def _flat(a):
    return a.data().reshape(-1) if isinstance(a, NDArray) \
        else jnp.asarray(a).reshape(-1)


def cov(m, y=None, rowvar=True, bias=False, ddof=None):
    args = [m] if y is None else [m, y]
    args = [x if isinstance(x, NDArray) else array(x) for x in args]
    if y is None:
        return _invoke(lambda x: jnp.cov(
            x.astype(jnp.float32), rowvar=rowvar, bias=bias, ddof=ddof),
            args, "_npi_cov")
    return _invoke(lambda x, yy: jnp.cov(
        x.astype(jnp.float32), yy.astype(jnp.float32), rowvar=rowvar,
        bias=bias, ddof=ddof), args, "_npi_cov")


def corrcoef(x, rowvar=True):
    if not isinstance(x, NDArray):
        x = array(x)
    return _invoke(lambda a: jnp.corrcoef(a.astype(jnp.float32),
                                          rowvar=rowvar),
                   [x], "_npi_corrcoef")


def ptp(a, axis=None, keepdims=False):
    if not isinstance(a, NDArray):
        a = array(a)
    return _invoke(lambda x: jnp.ptp(x, axis=_norm_axis(axis),
                                     keepdims=keepdims), [a], "_npi_ptp")


def _nan_reduce(name, jfn, with_ddof=False):
    def f(a, axis=None, ddof=0, keepdims=False):
        if not isinstance(a, NDArray):
            a = array(a)
        kw = {"axis": _norm_axis(axis), "keepdims": keepdims}
        if with_ddof:
            kw["ddof"] = ddof
        return _invoke(lambda x: jfn(x.astype(jnp.float32), **kw),
                       [a], "_npi_" + name)
    f.__name__ = name
    return f


nanmean = _nan_reduce("nanmean", jnp.nanmean)
nanstd = _nan_reduce("nanstd", jnp.nanstd, with_ddof=True)
nanvar = _nan_reduce("nanvar", jnp.nanvar, with_ddof=True)


def nanmax(a, axis=None, keepdims=False):
    if not isinstance(a, NDArray):
        a = array(a)
    return _invoke(lambda x: jnp.nanmax(x, axis=_norm_axis(axis),
                                        keepdims=keepdims),
                   [a], "_npi_nanmax")


def nanmin(a, axis=None, keepdims=False):
    if not isinstance(a, NDArray):
        a = array(a)
    return _invoke(lambda x: jnp.nanmin(x, axis=_norm_axis(axis),
                                        keepdims=keepdims),
                   [a], "_npi_nanmin")


def nanargmax(a, axis=None):
    if not isinstance(a, NDArray):
        a = array(a)
    return _invoke(lambda x: jnp.nanargmax(x, axis=axis), [a],
                   "_npi_nanargmax")


def nanargmin(a, axis=None):
    if not isinstance(a, NDArray):
        a = array(a)
    return _invoke(lambda x: jnp.nanargmin(x, axis=axis), [a],
                   "_npi_nanargmin")


def hanning(M, dtype="float32", ctx=None):
    return array(jnp.hanning(int(M)).astype(_to_jax_dtype(dtype)), ctx=ctx)


def hamming(M, dtype="float32", ctx=None):
    return array(jnp.hamming(int(M)).astype(_to_jax_dtype(dtype)), ctx=ctx)


def blackman(M, dtype="float32", ctx=None):
    return array(jnp.blackman(int(M)).astype(_to_jax_dtype(dtype)),
                 ctx=ctx)


def bartlett(M, dtype="float32", ctx=None):
    return array(jnp.bartlett(int(M)).astype(_to_jax_dtype(dtype)),
                 ctx=ctx)


def polyval(p, x):
    p = p if isinstance(p, NDArray) else array(p)
    x = x if isinstance(x, NDArray) else array(x)
    return _invoke(lambda pp, xx: jnp.polyval(pp.astype(jnp.float32),
                                              xx.astype(jnp.float32)),
                   [p, x], "_npi_polyval")


def ediff1d(ary, to_end=None, to_begin=None):
    ary = ary if isinstance(ary, NDArray) else array(ary)
    return _invoke(lambda x: jnp.ediff1d(
        x, to_end=None if to_end is None else jnp.asarray(to_end),
        to_begin=None if to_begin is None else jnp.asarray(to_begin)),
        [ary], "_npi_ediff1d")


def nan_to_num(x, copy=True, nan=0.0, posinf=None, neginf=None):
    was_nd = isinstance(x, NDArray)
    x = x if was_nd else array(x)
    res = _invoke(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf,
                                           neginf=neginf),
                  [x], "_npi_nan_to_num")
    if not copy and was_nd:
        x._adopt(res)  # documented in-place contract
        return x
    return res


def digitize(x, bins, right=False):
    x = x if isinstance(x, NDArray) else array(x)
    bins = bins if isinstance(bins, NDArray) else array(bins)
    return _invoke(lambda a, b: jnp.digitize(a, b, right=right),
                   [x, bins], "_npi_digitize")


def trapz(y, x=None, dx=1.0, axis=-1):
    y = y if isinstance(y, NDArray) else array(y)
    if x is None:
        return _invoke(lambda a: jnp.trapezoid(
            a.astype(jnp.float32), dx=dx, axis=axis), [y], "_npi_trapz")
    x = x if isinstance(x, NDArray) else array(x)
    return _invoke(lambda a, b: jnp.trapezoid(
        a.astype(jnp.float32), b.astype(jnp.float32), axis=axis),
        [y, x], "_npi_trapz")


def isin(element, test_elements, assume_unique=False, invert=False):
    element = element if isinstance(element, NDArray) else array(element)
    test_elements = test_elements if isinstance(test_elements, NDArray) \
        else array(test_elements)
    return _invoke(lambda e, t: jnp.isin(e, t, invert=invert),
                   [element, test_elements], "_npi_isin")


def in1d(ar1, ar2, assume_unique=False, invert=False):
    return isin(ar1, ar2, assume_unique=assume_unique,
                invert=invert).reshape(-1)


def _set_op(onp_name):
    def f(ar1, ar2, assume_unique=False):
        # single implementation lives on the registry op (host path for
        # data-dependent output sizes, ops/npi.py _set_op_override)
        a = ar1 if isinstance(ar1, NDArray) else array(ar1)
        b = ar2 if isinstance(ar2, NDArray) else array(ar2)
        out = _reg.invoke("_npi_" + onp_name, [a, b],
                          {"assume_unique": assume_unique})
        if isinstance(out, (list, tuple)):
            out = out[0]
        return _as_np(out)

    f.__name__ = onp_name
    return f


intersect1d = _set_op("intersect1d")
union1d = _set_op("union1d")
setdiff1d = _set_op("setdiff1d")
setxor1d = _set_op("setxor1d")


for _extra in ("copysign", "fmod", "heaviside", "gcd", "lcm",
               "logaddexp", "hypot", "nextafter"):
    if _extra not in globals():
        globals()[_extra] = _make_binary(_extra)
for _extra in ("deg2rad", "rad2deg", "signbit", "cbrt", "positive",
               "fabs", "spacing"):
    if _extra not in globals() and hasattr(jnp, _extra):
        globals()[_extra] = _make_unary(_extra)
del _extra


# ---------------------------------------------------------------------------
# remaining reference-surface stragglers (multiarray.py grep-diff, round 4)
# ---------------------------------------------------------------------------


def append(arr, values, axis=None):
    return _invoke(lambda a, v: jnp.append(a, v, axis=axis),
                   [arr, values], "_np_append")


def around(x, decimals=0, out=None):
    res = round(x, decimals)
    if out is not None:
        out._adopt(res)
        return out
    return res


def ravel(x, order="C"):
    if order not in ("C", "K", "A"):
        raise MXNetError("ravel: only C-order supported on XLA buffers")
    return _invoke(lambda a: jnp.ravel(a), [x], "_np_ravel")


def fliplr(m):
    return _invoke(jnp.fliplr, [m], "_np_fliplr")


def flipud(m):
    return _invoke(jnp.flipud, [m], "_np_flipud")


def empty_like(prototype, dtype=None, order="C"):
    p = prototype if isinstance(prototype, NDArray) else array(prototype)
    return empty(p.shape, dtype=dtype or p.dtype)


def column_stack(tup):
    ins = [t if isinstance(t, NDArray) else array(t) for t in tup]
    (out,) = _reg.invoke_fn(lambda *xs: (jnp.column_stack(xs),), ins,
                            op_name="_np_column_stack")
    return _as_np(out)


def row_stack(tup):
    return vstack(tup)


def hsplit(ary, indices_or_sections):
    outs = _reg.invoke_fn(
        lambda x: tuple(jnp.hsplit(x, indices_or_sections)),
        [ary if isinstance(ary, NDArray) else array(ary)],
        op_name="_np_hsplit")
    return [_as_np(o) for o in outs]


def vsplit(ary, indices_or_sections):
    outs = _reg.invoke_fn(
        lambda x: tuple(jnp.vsplit(x, indices_or_sections)),
        [ary if isinstance(ary, NDArray) else array(ary)],
        op_name="_np_vsplit")
    return [_as_np(o) for o in outs]


def broadcast_arrays(*args):
    ins = [a if isinstance(a, NDArray) else array(a) for a in args]
    outs = _reg.invoke_fn(lambda *xs: tuple(jnp.broadcast_arrays(*xs)),
                          ins, op_name="_np_broadcast_arrays")
    return [_as_np(o) for o in outs]


def vdot(a, b):
    return _invoke(lambda x, y: jnp.vdot(x, y), [a, b], "_np_vdot")


def ldexp(x1, x2):
    return _invoke(lambda a, b: jnp.ldexp(a, b), [x1, x2], "_np_ldexp")


def delete(arr, obj, axis=None):
    """Static-index delete (slice/int/array of indices known at call
    time — XLA needs static output shapes, so ``obj`` must be
    concrete)."""
    if isinstance(obj, NDArray):
        obj = obj.asnumpy()
    elif isinstance(obj, (list, tuple)):
        obj = _onp.asarray(obj)
    if isinstance(obj, _onp.ndarray) and obj.dtype != _onp.bool_:
        obj = obj.astype(_onp.int64)  # bool masks keep mask semantics
    return _invoke(lambda a: jnp.delete(a, obj, axis=axis), [arr],
                   "_np_delete")


def indices(dimensions, dtype=None):
    res = _onp.indices(dimensions)
    return array(res if dtype is None else res.astype(dtype))


def resize(a, new_shape):
    """NumPy-semantics resize: repeat-fill when growing (differs from
    ndarray.resize's zero-fill, same as the reference's np.resize)."""
    return _invoke(lambda x: jnp.resize(x, new_shape), [a], "_np_resize")


def unravel_index(idx, shape, order="C"):
    if order != "C":
        raise MXNetError("unravel_index: only C-order supported")
    ins = [idx if isinstance(idx, NDArray) else array(idx)]
    outs = _reg.invoke_fn(
        lambda i: tuple(jnp.unravel_index(i.astype(jnp.int64), shape)),
        ins, op_name="_np_unravel_index")
    return tuple(_as_np(o) for o in outs)


def _check_bitwise_dtype(fn_name, *arrs):
    for a in arrs:
        arr = a if isinstance(a, NDArray) else array(a)
        if _onp.dtype(arr.dtype).kind == "f":
            raise TypeError(
                "%s not supported for float input (dtype %s) — numpy "
                "semantics: bitwise ops require integer/bool operands"
                % (fn_name, arr.dtype))


def bitwise_not(x):
    _check_bitwise_dtype("bitwise_not", x)
    return _invoke(jnp.bitwise_not, [x], "_np_bitwise_not")


invert = bitwise_not


def bitwise_or(x1, x2):
    _check_bitwise_dtype("bitwise_or", x1, x2)
    return _invoke(jnp.bitwise_or, [x1, x2], "_np_bitwise_or")


def bitwise_xor(x1, x2):
    _check_bitwise_dtype("bitwise_xor", x1, x2)
    return _invoke(jnp.bitwise_xor, [x1, x2], "_np_bitwise_xor")


def shares_memory(a, b, max_work=None):
    """True iff the two arrays alias one device buffer.  XLA arrays are
    immutable and views copy, so aliasing == same underlying buffer
    (the reference's answer is likewise identity-ish: its
    shares_memory equals may_share_memory)."""
    da = a.data() if isinstance(a, NDArray) else None
    db = b.data() if isinstance(b, NDArray) else None
    return bool(a is b or (da is not None and da is db))


may_share_memory = shares_memory


def genfromtxt(*args, **kwargs):
    """Host-side text loader (delegates to numpy, wraps the result)."""
    return array(_onp.genfromtxt(*args, **kwargs))


def set_printoptions(*args, **kwargs):
    """Printing is host-side numpy formatting; delegate directly."""
    _onp.set_printoptions(*args, **kwargs)


from . import linalg  # noqa: E402,F401
from . import random  # noqa: E402,F401
