"""``mx.np.linalg`` (parity: python/mxnet/numpy/linalg.py over the
``_npi_*``/``src/operator/numpy/linalg`` kernels — here lowered straight
to jnp.linalg through the traced invoke_fn path, so they are
differentiable and engine-tracked)."""
from __future__ import annotations

import jax.numpy as jnp

from . import _invoke, _as_np, ndarray, array
from ..ndarray.ndarray import NDArray
from ..ops import registry as _reg


def _one(fn, a, name):
    return _invoke(fn, [a if isinstance(a, NDArray) else array(a)], name)


def norm(x, ord=None, axis=None, keepdims=False):
    return _one(lambda a: jnp.linalg.norm(a, ord=ord, axis=axis,
                                          keepdims=keepdims), x,
                "_np_linalg_norm")


def inv(a):
    return _one(jnp.linalg.inv, a, "_np_linalg_inv")


def det(a):
    return _one(jnp.linalg.det, a, "_np_linalg_det")


def slogdet(a):
    outs = _reg.invoke_fn(
        lambda x: tuple(jnp.linalg.slogdet(x)),
        [a if isinstance(a, NDArray) else array(a)],
        op_name="_np_linalg_slogdet")
    return tuple(_as_np(o) for o in outs)


def cholesky(a):
    return _one(jnp.linalg.cholesky, a, "_np_linalg_cholesky")


def svd(a):
    outs = _reg.invoke_fn(
        lambda x: tuple(jnp.linalg.svd(x, full_matrices=False)),
        [a if isinstance(a, NDArray) else array(a)],
        op_name="_np_linalg_svd")
    return tuple(_as_np(o) for o in outs)


def eigh(a):
    outs = _reg.invoke_fn(
        lambda x: tuple(jnp.linalg.eigh(x)),
        [a if isinstance(a, NDArray) else array(a)],
        op_name="_np_linalg_eigh")
    return tuple(_as_np(o) for o in outs)


def eigvalsh(a):
    return _one(jnp.linalg.eigvalsh, a, "_np_linalg_eigvalsh")


def solve(a, b):
    return _invoke(jnp.linalg.solve,
                   [a if isinstance(a, NDArray) else array(a),
                    b if isinstance(b, NDArray) else array(b)],
                   "_np_linalg_solve")


def lstsq(a, b, rcond=None):
    outs = _reg.invoke_fn(
        lambda x, y: tuple(jnp.linalg.lstsq(x, y, rcond=rcond)),
        [a if isinstance(a, NDArray) else array(a),
         b if isinstance(b, NDArray) else array(b)],
        op_name="_np_linalg_lstsq")
    return tuple(_as_np(o) for o in outs)


def pinv(a, rcond=1e-15):
    return _one(lambda x: jnp.linalg.pinv(x, rcond=rcond), a,
                "_np_linalg_pinv")


def matrix_rank(a, tol=None):
    return _one(lambda x: jnp.linalg.matrix_rank(x, tol=tol), a,
                "_np_linalg_matrix_rank")


def qr(a):
    outs = _reg.invoke_fn(
        lambda x: tuple(jnp.linalg.qr(x)),
        [a if isinstance(a, NDArray) else array(a)],
        op_name="_np_linalg_qr")
    return tuple(_as_np(o) for o in outs)


def tensorinv(a, ind=2):
    return _one(lambda x: jnp.linalg.tensorinv(x, ind=ind), a,
                "_np_linalg_tensorinv")


def tensorsolve(a, b, axes=None):
    return _invoke(lambda x, y: jnp.linalg.tensorsolve(x, y, axes=axes),
                   [a if isinstance(a, NDArray) else array(a),
                    b if isinstance(b, NDArray) else array(b)],
                   "_np_linalg_tensorsolve")
