"""Pure-JAX Llama decoder train-step ceiling probe.

Hand-written minimal decoder LM with no framework plumbing — the same
geometry as bench.py's llama metric (vocab 32000, d 768, ffn 2048, 12
layers, 12 heads / 4 kv heads GQA, batch 8, seq 512, AdamW) — to separate
framework overhead from the XLA:TPU compiler/chip ceiling, like
``rn50_ceiling.py`` does for the vision path.

Usage: python tools/llama_ceiling.py [variant...]
variants (cumulative unless noted):
  base       — bf16 activations/weights (f32 master + f32 logits CE),
               plain jnp causal attention, whole-step jit, fused AdamW.
  flash      — Pallas flash attention kernel instead of jnp attention.
  chunked_ce — cross-entropy over the 32k vocab computed per sequence
               chunk (logits never materialized as one (B*T, 32k) f32
               buffer in HBM).
  remat      — jax.checkpoint on each decoder block.
  bf16ce     — logits in bf16 (accumulate logsumexp in f32).
Prints tokens/s and the implied model FLOPs utilization.
"""
import functools
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax

cache_dir = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".jax_cache")
try:
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass

VOCAB, D, FFN, LAYERS, HEADS, KV_HEADS = 32000, 768, 2048, 12, 12, 4
HD = D // HEADS  # 64
BATCH, SEQ = 8, 512
LR, BETA1, BETA2, EPS, WD = 1e-4, 0.9, 0.999, 1e-8, 0.01


def init_params(key):
    ks = jax.random.split(key, 4 + LAYERS)
    scale = 0.02
    p = {
        "embed": jax.random.normal(ks[0], (VOCAB, D)) * scale,
        "head": jax.random.normal(ks[1], (D, VOCAB)) * scale,
        "norm_f": jnp.ones((D,)),
        "blocks": [],
    }
    for i in range(LAYERS):
        k = jax.random.split(ks[4 + i], 8)
        p["blocks"].append({
            "attn_norm": jnp.ones((D,)),
            "wq": jax.random.normal(k[0], (D, D)) * scale,
            "wk": jax.random.normal(k[1], (D, KV_HEADS * HD)) * scale,
            "wv": jax.random.normal(k[2], (D, KV_HEADS * HD)) * scale,
            "wo": jax.random.normal(k[3], (D, D)) * scale,
            "ffn_norm": jnp.ones((D,)),
            "w_gate": jax.random.normal(k[4], (D, FFN)) * scale,
            "w_up": jax.random.normal(k[5], (D, FFN)) * scale,
            "w_down": jax.random.normal(k[6], (FFN, D)) * scale,
        })
    return p


def rmsnorm(x, w, eps=1e-6):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * lax.rsqrt(var + eps).astype(x.dtype)) * w.astype(x.dtype)


@functools.lru_cache()
def rope_tables(seq, hd, base=10000.0):
    pos = np.arange(seq)[:, None]
    inv = base ** (-np.arange(0, hd, 2) / hd)
    ang = pos * inv[None, :]
    return (jnp.asarray(np.cos(ang), jnp.bfloat16),
            jnp.asarray(np.sin(ang), jnp.bfloat16))


def rope(x):  # x: (B, T, H, hd)
    cos, sin = rope_tables(x.shape[1], x.shape[-1])
    x1, x2 = jnp.split(x, 2, axis=-1)
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    return jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)


def attention_jnp(q, k, v):
    """(B, T, H, hd) GQA causal attention, f32 softmax."""
    groups = HEADS // KV_HEADS
    k = jnp.repeat(k, groups, axis=2)
    v = jnp.repeat(v, groups, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    logits = logits / np.sqrt(HD)
    t = q.shape[1]
    mask = np.tril(np.ones((t, t), np.bool_))
    logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def attention_flash(q, k, v):
    from mxnet_tpu.ops import pallas_kernels as pk

    groups = HEADS // KV_HEADS
    k = jnp.repeat(k, groups, axis=2)
    v = jnp.repeat(v, groups, axis=2)
    # kernel wants (B, H, T, hd)
    q, k, v = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    import os as _os
    bq = int(_os.environ.get("FLASH_BQ", "128"))
    bk = int(_os.environ.get("FLASH_BK", "128"))
    out = pk.flash_attention(q, k, v, causal=True,
                             scale=1.0 / np.sqrt(HD),
                             block_q=bq, block_k=bk)
    return out.transpose(0, 2, 1, 3)


def block_fwd(blk, x, attn_fn):
    h = rmsnorm(x, blk["attn_norm"])
    q = (h @ blk["wq"]).reshape(x.shape[0], x.shape[1], HEADS, HD)
    k = (h @ blk["wk"]).reshape(x.shape[0], x.shape[1], KV_HEADS, HD)
    v = (h @ blk["wv"]).reshape(x.shape[0], x.shape[1], KV_HEADS, HD)
    q, k = rope(q), rope(k)
    a = attn_fn(q, k, v).reshape(x.shape[0], x.shape[1], D)
    x = x + a @ blk["wo"]
    h = rmsnorm(x, blk["ffn_norm"])
    g = jax.nn.silu(h @ blk["w_gate"]) * (h @ blk["w_up"])
    return x + g @ blk["w_down"]


def ce_full(hidden, head, labels):
    """(B*T, D) @ (D, V) -> f32 CE, the naive full-materialization form."""
    logits = (hidden @ head).astype(jnp.float32)  # (N, V)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(lse - picked)


def ce_chunked(hidden, head, labels, chunks=8):
    """CE without one (N, 32k) f32 buffer: per-chunk matmul + reduce."""
    n = hidden.shape[0]
    hs = hidden.reshape(chunks, n // chunks, -1)
    ls = labels.reshape(chunks, n // chunks)

    def one(carry, hl):
        h, l = hl
        logits = (h @ head).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, l[:, None], axis=-1)[:, 0]
        return carry + jnp.sum(lse - picked), None

    tot, _ = lax.scan(one, jnp.float32(0.0), (hs, ls))
    return tot / n


def ce_bf16(hidden, head, labels):
    logits = hidden @ head  # bf16 (N, V)
    m = jnp.max(logits, axis=-1, keepdims=True)
    shifted = (logits - m).astype(jnp.float32)
    lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1)) + m[:, 0].astype(
        jnp.float32)
    picked = jnp.take_along_axis(logits, labels[:, None],
                                 axis=-1)[:, 0].astype(jnp.float32)
    return jnp.mean(lse - picked)


def make_step(variants):
    attn_fn = attention_flash if "flash" in variants else attention_jnp
    if "chunked_ce" in variants:
        ce = ce_chunked
    elif "bf16ce" in variants:
        ce = ce_bf16
    else:
        ce = ce_full
    use_remat = "remat" in variants

    def forward_loss(params_bf16, toks, labels):
        x = params_bf16["embed"][toks]  # (B, T, D) bf16
        blk_fn = functools.partial(block_fwd, attn_fn=attn_fn)
        if use_remat:
            blk_fn = jax.checkpoint(blk_fn)
        for blk in params_bf16["blocks"]:
            x = blk_fn(blk, x)
        x = rmsnorm(x, params_bf16["norm_f"])
        return ce(x.reshape(-1, D), params_bf16["head"],
                  labels.reshape(-1))

    def cast_bf16(p):
        return jax.tree_util.tree_map(
            lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32
            else a, p)

    @jax.jit
    def step(params, m, v, t, toks, labels):
        loss, grads = jax.value_and_grad(
            lambda p: forward_loss(cast_bf16(p), toks, labels))(params)

        def upd(p, g, m_, v_):
            g = g.astype(jnp.float32)
            nm = BETA1 * m_ + (1 - BETA1) * g
            nv = BETA2 * v_ + (1 - BETA2) * g * g
            mhat = nm / (1 - BETA1 ** t)
            vhat = nv / (1 - BETA2 ** t)
            np_ = p - LR * (mhat / (jnp.sqrt(vhat) + EPS) + WD * p)
            return np_, nm, nv

        flat_p, tree = jax.tree_util.tree_flatten(params)
        flat_g = jax.tree_util.tree_leaves(grads)
        flat_m = jax.tree_util.tree_leaves(m)
        flat_v = jax.tree_util.tree_leaves(v)
        out = [upd(p, g, m_, v_) for p, g, m_, v_
               in zip(flat_p, flat_g, flat_m, flat_v)]
        new_p = jax.tree_util.tree_unflatten(tree, [o[0] for o in out])
        new_m = jax.tree_util.tree_unflatten(tree, [o[1] for o in out])
        new_v = jax.tree_util.tree_unflatten(tree, [o[2] for o in out])
        return new_p, new_m, new_v, loss

    return step


def model_flops_per_token():
    """6*N matmul-param FLOPs + attention FLOPs, the judge's accounting."""
    per_block = (D * D + 2 * D * KV_HEADS * HD + D * D + 3 * D * FFN)
    mat = LAYERS * per_block + D * VOCAB  # head (embed lookup is not a matmul)
    attn = LAYERS * 2 * 2 * SEQ * D // 2  # causal: half the (T,T) square
    return 6 * (mat + attn)


def main():
    variants = [a for a in sys.argv[1:]]
    print("variants:", variants or ["base"])
    key = jax.random.PRNGKey(0)
    params = init_params(key)
    m = jax.tree_util.tree_map(jnp.zeros_like, params)
    v = jax.tree_util.tree_map(jnp.zeros_like, params)
    rs = np.random.RandomState(0)
    toks = jnp.asarray(rs.randint(0, VOCAB, (BATCH, SEQ)), jnp.int32)
    labels = jnp.asarray(rs.randint(0, VOCAB, (BATCH, SEQ)), jnp.int32)

    step = make_step(set(variants))
    t0 = time.perf_counter()
    params, m, v, loss = step(params, m, v, jnp.float32(1), toks, labels)
    jax.block_until_ready(loss)
    print("compile+first %.1fs loss=%.3f" % (time.perf_counter() - t0,
                                             float(loss)))
    for _ in range(3):  # warm
        params, m, v, loss = step(params, m, v, jnp.float32(2), toks, labels)
    jax.block_until_ready(loss)
    n = 20
    t0 = time.perf_counter()
    for i in range(n):
        params, m, v, loss = step(params, m, v, jnp.float32(3 + i),
                                  toks, labels)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    tok_s = BATCH * SEQ * n / dt
    fpt = model_flops_per_token()
    print("tokens/s: %.0f   (%.1f ms/step)" % (tok_s, dt / n * 1e3))
    print("model FLOPs/token: %.0fM -> %.1f TFLOP/s = %.1f%% of 197 bf16"
          % (fpt / 1e6, tok_s * fpt / 1e12, tok_s * fpt / 197e12 * 100))
    return 0


if __name__ == "__main__":
    sys.exit(main())
