#!/usr/bin/env python
"""Run a test many times to surface flakiness
(parity: reference tools/flakiness_checker.py).

Usage:
    python tools/flakiness_checker.py test_module.test_name [-n 500]
    python tools/flakiness_checker.py tests/test_gluon.py::test_dense
"""
from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys

DEFAULT_NUM_TRIALS = 500


def find_test_path(test_file):
    """Locate a test file by name under tests/ (reference:
    flakiness_checker.py:55)."""
    test_file += ".py"
    test_path = os.path.split(test_file)
    top = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests")
    for root, _dirs, files in os.walk(top):
        if test_path[1] in files:
            return os.path.join(root, test_path[1])
    raise FileNotFoundError(
        "could not find %s under %s" % (test_path[1], top))


def run_test_trials(args):
    if "/" in args.test or args.test.endswith(".py") \
            or "::" in args.test:
        test_spec = args.test
    else:
        # reference syntax: test_module.test_name
        mod, _, name = args.test.rpartition(".")
        test_spec = "%s::%s" % (find_test_path(mod), name)
    env = dict(os.environ)
    if args.seed is not None:
        env["MXNET_TEST_SEED"] = str(args.seed)
    print("running %s for %d trials" % (test_spec, args.trials))
    cmd = [sys.executable, "-m", "pytest", "-q", "-x",
           "--count=%d" % args.trials, test_spec] \
        if args.use_count_plugin else None
    failures = 0
    if cmd is not None:
        return subprocess.call(cmd, env=env)
    for i in range(args.trials):
        rc = subprocess.call(
            [sys.executable, "-m", "pytest", "-q", test_spec],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        if rc != 0:
            failures += 1
            print("trial %d FAILED" % i)
    print("%d/%d trials failed" % (failures, args.trials))
    return 1 if failures else 0


def parse_args():
    ap = argparse.ArgumentParser(
        description="Check test flakiness by repetition")
    ap.add_argument("test",
                    help="file.py::test, tests path, or module.test_name")
    ap.add_argument("-n", "--trials", type=int,
                    default=DEFAULT_NUM_TRIALS,
                    help="number of runs (default %d)"
                    % DEFAULT_NUM_TRIALS)
    ap.add_argument("-s", "--seed", type=int, default=None,
                    help="fixed MXNET_TEST_SEED for every run")
    ap.add_argument("--use-count-plugin", action="store_true",
                    help="use pytest-repeat's --count instead of "
                         "spawning per-trial processes")
    return ap.parse_args()


if __name__ == "__main__":
    sys.exit(run_test_trials(parse_args()))
