"""Capture a device trace of the ResNet-50 train step and print top ops.

Usage: python tools/profile_resnet.py [batch]
Writes the xplane under /tmp/rn50_trace and prints the op-profile table
(tensorboard_plugin_profile) so hotspots are visible without tensorboard.
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    cache_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel, amp
    from mxnet_tpu.gluon.model_zoo import vision

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    mx.random.seed(0)
    net = vision.resnet50_v1()
    print("layout:", net._layout, file=sys.stderr)
    net.initialize(mx.init.Xavier())
    amp.init("bfloat16")
    amp.convert_hybrid_block(net)
    step = parallel.JitTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        "sgd", {"learning_rate": 0.1, "momentum": 0.9})
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch, 3, 224, 224), jnp.bfloat16)
    y = rng.randint(0, 1000, batch).astype(np.float32)
    t0 = time.perf_counter()
    loss = step.step(x, y)
    jax.block_until_ready(loss)
    print("first step %.1fs" % (time.perf_counter() - t0), file=sys.stderr)
    loss = step.step_n(10, x, y)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    loss = step.step_n(10, x, y)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    print("10 steps: %.3fs -> %.1f img/s" % (dt, batch * 10 / dt),
          file=sys.stderr)

    logdir = "/tmp/rn50_trace"
    os.system("rm -rf %s" % logdir)
    with jax.profiler.trace(logdir):
        loss = step.step_n(10, x, y)
        jax.block_until_ready(loss)

    # find the xplane file
    xplane = None
    for root, _, files in os.walk(logdir):
        for f in files:
            if f.endswith(".xplane.pb"):
                xplane = os.path.join(root, f)
    print("xplane:", xplane, file=sys.stderr)


if __name__ == "__main__":
    main()
