#!/usr/bin/env python
"""mxplan — dry-run the SPMD auto-sharding planner from the command line.

Plans against ABSTRACT mesh axes (``--mesh data=4,model=2``): no
accelerator (and no devices at all beyond host CPU) is needed, so a
laptop can plan a pod.  The same cost model drives
``JitTrainStep(rules="auto")``; this tool is the inspection surface::

    python tools/mxplan.py --mesh data=4,model=2 --model llama_small
    python tools/mxplan.py --mesh data=8 --model mlp --capacity 64MiB
    python tools/mxplan.py --mesh data=4,model=2 --params params.json

``--params`` takes a JSON list of ``[name, shape]`` or
``[name, shape, dtype]`` entries.  ``--format json`` emits
``Plan.as_dict()`` with sorted keys — byte-identical across runs for the
same inputs (the CI determinism contract).  Exit status: 0 when the
chosen plan fits the capacity, 3 when no candidate does (predicted
per-device OOM — the runtime twin of mxlint SP1001), 2 on usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

# planning is pure byte maths over abstract axes; never touch accelerators
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_SIZE_SUFFIX = {"": 1, "B": 1, "KIB": 1 << 10, "MIB": 1 << 20,
                "GIB": 1 << 30, "KB": 10 ** 3, "MB": 10 ** 6, "GB": 10 ** 9}


def parse_mesh(s):
    """``data=4,model=2`` -> {"data": 4, "model": 2} (order preserved)."""
    axes = {}
    for part in s.split(","):
        part = part.strip()
        if not part:
            continue
        name, eq, size = part.partition("=")
        if not eq or not size.strip().isdigit():
            raise ValueError(
                "bad mesh axis %r (expected name=size, e.g. data=4)" % part)
        axes[name.strip()] = int(size.strip())
    if not axes:
        raise ValueError("empty mesh (expected e.g. data=4,model=2)")
    return axes


def parse_capacity(s):
    """``64MiB`` / ``16GB`` / ``123456`` -> bytes."""
    t = s.strip().upper()
    for suf in sorted(_SIZE_SUFFIX, key=len, reverse=True):
        if suf and t.endswith(suf):
            num = t[:-len(suf)].strip()
            if num.replace(".", "", 1).isdigit():
                return int(float(num) * _SIZE_SUFFIX[suf])
    if t.isdigit():
        return int(t)
    raise ValueError("bad capacity %r (expected bytes or e.g. 64MiB)" % s)


def _model_params(name):
    """Built-in parameter trees.  llama_small needs one throwaway forward
    to resolve deferred shapes — host CPU, tiny batch."""
    from mxnet_tpu import nd

    if name == "mlp":
        from mxnet_tpu.gluon import nn

        net = nn.HybridSequential()
        net.add(nn.Dense(64, activation="relu"), nn.Dense(10))
        net.initialize()
        net(nd.ones((1, 32)))
    elif name == "llama_small":
        from mxnet_tpu.gluon.model_zoo import llama

        net = llama.llama_small()
        net.initialize()
        net(nd.array([[1, 2, 3, 4]], dtype="int32"))
    else:
        raise ValueError("unknown --model %r (llama_small, mlp)" % name)
    return [(p.name, tuple(p.shape),
             str(getattr(p, "dtype", "float32") or "float32"))
            for p in net.collect_params().values()]


def _json_params(path):
    with open(path) as f:
        doc = json.load(f)
    out = []
    for entry in doc:
        name, shape = entry[0], tuple(int(d) for d in entry[1])
        dtype = entry[2] if len(entry) > 2 else "float32"
        out.append((name, shape, dtype))
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="mxplan", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--mesh", required=True, metavar="AXES",
                    help="abstract mesh axes, e.g. data=4,model=2")
    ap.add_argument("--model", default=None,
                    choices=("llama_small", "mlp"),
                    help="built-in parameter tree to plan")
    ap.add_argument("--params", default=None, metavar="FILE",
                    help="JSON [[name, shape, dtype?], ...] to plan "
                         "instead of --model")
    ap.add_argument("--capacity", default=None, metavar="BYTES",
                    help="per-device budget (e.g. 64MiB); default: "
                         "$MXNET_PLANNER_CAPACITY_BYTES, else "
                         "unconstrained")
    ap.add_argument("--tokens", type=int, default=None, metavar="N",
                    help="tokens per step (sizes the tp activation "
                         "all-reduces)")
    ap.add_argument("--slots", type=int, default=0, metavar="N",
                    help="optimizer state arrays per weight (0 sgd, "
                         "1 momentum, 2 adam)")
    ap.add_argument("--data-axis", default="data", metavar="AXIS")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)

    try:
        axes = parse_mesh(args.mesh)
        capacity = parse_capacity(args.capacity) if args.capacity else None
    except ValueError as e:
        ap.error(str(e))
    if (args.model is None) == (args.params is None):
        ap.error("pass exactly one of --model or --params")

    from mxnet_tpu import planner

    try:
        params = (_json_params(args.params) if args.params
                  else _model_params(args.model))
    except (OSError, ValueError, KeyError, IndexError) as e:
        ap.error("could not load parameters: %s" % e)

    pl = planner.plan(params, axes, data_axis=args.data_axis,
                      capacity_bytes=capacity, step_tokens=args.tokens,
                      optimizer_slots=args.slots)
    if args.format == "json":
        print(json.dumps(pl.as_dict(), indent=2, sort_keys=True))
    else:
        print(pl.explain())
    return 0 if pl.feasible else 3


if __name__ == "__main__":
    sys.exit(main())
