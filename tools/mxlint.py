#!/usr/bin/env python
"""mxlint — framework-aware static analysis for mxnet_tpu code.

Runs the tracing-safety (TS1xx), host-sync (HS2xx), collective-
consistency (CC6xx), robustness (RB7xx), cache-key (CS8xx), sharding
(SH9xx), planner (SP10xx), concurrency-discipline (CD11xx) and
lifecycle (RL12xx) passes over the given files/directories, plus the
op-registry consistency pass (RC3xx) when the framework imports.
``--pass SP10`` or ``--pass RL`` (alias ``--only``; comma-separated
bands, families or rule ids) runs a selection in isolation.
Explicitly-passed ``.json`` files are verified as serialized Symbol
graphs with the per-node GS5xx pass.  The repo's own tree is a permanent
lint target::

    python tools/mxlint.py mxnet_tpu/ examples/
    python tools/mxlint.py model-symbol.json

Exit status (stable, scripted against by CI): 0 when clean (after
suppressions and the ``--fail-on`` threshold), 1 when any finding at or
above the threshold remains, 2 on usage error.  See
docs/static_analysis.md for the rule catalogue and suppression syntax
(`# mxlint: allow-host-sync`, `# mxlint: disable=TS101`,
tools/mxlint_suppressions.txt).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

# the linter only needs host CPU; don't touch accelerators just to parse ASTs
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="mxlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", help="files or directories to lint")
    ap.add_argument("--strict", action="store_true",
                    help="enable advisory rules (HS204)")
    ap.add_argument("--fail-on", choices=("note", "warn", "error"),
                    default="warn", metavar="SEVERITY",
                    help="minimum severity that fails the run (note|warn|"
                         "error; default: warn — advisory notes print but "
                         "don't fail).  Findings below the threshold are "
                         "still printed.")
    ap.add_argument("--no-registry-check", action="store_true",
                    help="skip the RC3xx registry consistency pass")
    ap.add_argument("--no-probe", action="store_true",
                    help="registry pass: structural checks only, no "
                         "jax.eval_shape probing")
    ap.add_argument("--pass", "--only", dest="only", default=None,
                    metavar="SELECTION",
                    help="run only the selected passes/rules: comma-"
                         "separated bands (SH), families (SP10) or full "
                         "rule ids (TS101).  Other passes don't run; the "
                         "RC3xx registry pass runs only when RC is "
                         "selected (or no selection is given).")
    ap.add_argument("--suppressions", default=None, metavar="FILE",
                    help="suppression file (default: "
                         "tools/mxlint_suppressions.txt if present)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    args = ap.parse_args(argv)

    from mxnet_tpu.analysis import (RULES, lint_paths, check_registry,
                                    normalize_only, rule_selected,
                                    severity_at_least, verify_symbol_file)

    if args.list_rules:
        for rid in sorted(RULES):
            slug, default_on, doc = RULES[rid]
            print("%s  %-28s %s%s" % (rid, slug, doc,
                                      "" if default_on else "  [--strict]"))
        return 0

    if not args.paths:
        ap.error("no paths given (try: python tools/mxlint.py mxnet_tpu/)")

    try:
        only = normalize_only(args.only)
    except ValueError as e:
        ap.error(str(e))

    def band_on(band):
        return only is None or any(t.startswith(band) or band.startswith(t)
                                   for t in only)

    # explicitly-passed .json files are serialized Symbol graphs (GS5xx);
    # directory walks stay .py-only
    sym_files = [p for p in args.paths
                 if os.path.isfile(p) and p.endswith(".json")]
    py_paths = [p for p in args.paths if p not in sym_files]

    findings = lint_paths(py_paths, strict=args.strict,
                          suppressions=args.suppressions,
                          relative_to=_REPO_ROOT,
                          only=only) if py_paths else []
    for p in sym_files:
        findings.extend(
            f for f in verify_symbol_file(
                p, relative_to=_REPO_ROOT, suppressions=args.suppressions)
            if rule_selected(f.rule, only))
    if not args.no_registry_check and band_on("RC"):
        try:
            findings.extend(check_registry(suppressions=args.suppressions,
                                           probe=not args.no_probe,
                                           strict=args.strict))
        except Exception as e:
            print("mxlint: registry check skipped (%s: %s)"
                  % (type(e).__name__, e), file=sys.stderr)

    if args.format == "json":
        print(json.dumps([f.as_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f)
        n = len(findings)
        print("mxlint: %d finding%s" % (n, "" if n == 1 else "s"))
    return 1 if any(severity_at_least(f, args.fail_on)
                    for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
