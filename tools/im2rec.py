#!/usr/bin/env python
"""Pack an image folder into RecordIO (parity: reference tools/im2rec.py).

Makes a ``.lst`` (index  label  relpath), a ``.rec`` of packed
(IRHeader + encoded image) records, and a ``.idx`` for random access.
Decoding uses PIL instead of OpenCV; records are JPEG passthrough when
the source already is JPEG (no re-encode), matching im2rec's default.

Usage:
    python tools/im2rec.py PREFIX IMAGE_ROOT [--list] [--resize N]
        [--quality Q] [--shuffle]
"""
from __future__ import annotations

import argparse
import io
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_EXTS = {".jpg", ".jpeg", ".png", ".bmp"}


def find_images(root):
    """(relpath, label) pairs; label = sorted subdirectory index."""
    classes = sorted(
        d for d in os.listdir(root)
        if os.path.isdir(os.path.join(root, d)))
    label_of = {c: i for i, c in enumerate(classes)}
    items = []
    if classes:
        for c in classes:
            for fn in sorted(os.listdir(os.path.join(root, c))):
                if os.path.splitext(fn)[1].lower() in _EXTS:
                    items.append((os.path.join(c, fn), label_of[c]))
    else:
        for fn in sorted(os.listdir(root)):
            if os.path.splitext(fn)[1].lower() in _EXTS:
                items.append((fn, 0))
    return items


def write_list(prefix, items):
    with open(prefix + ".lst", "w") as f:
        for i, (rel, label) in enumerate(items):
            f.write("%d\t%f\t%s\n" % (i, float(label), rel))


def read_list(prefix):
    items = []
    with open(prefix + ".lst") as f:
        for line in f:
            idx, label, rel = line.rstrip("\n").split("\t")
            items.append((int(idx), float(label), rel))
    return items


def encode_image(path, resize=0, quality=95):
    from PIL import Image

    raw = open(path, "rb").read()
    ext = os.path.splitext(path)[1].lower()
    if not resize and ext in (".jpg", ".jpeg"):
        return raw  # passthrough, like the reference default
    img = Image.open(io.BytesIO(raw)).convert("RGB")
    if resize:
        w, h = img.size
        scale = resize / min(w, h)
        img = img.resize((max(1, int(w * scale)), max(1, int(h * scale))))
    buf = io.BytesIO()
    img.save(buf, format="JPEG", quality=quality)
    return buf.getvalue()


def make_rec(prefix, root, items, resize=0, quality=95):
    from mxnet_tpu import recordio

    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for idx, label, rel in items:
        data = encode_image(os.path.join(root, rel), resize, quality)
        header = recordio.IRHeader(0, label, idx, 0)
        rec.write_idx(idx, recordio.pack(header, data))
    rec.close()


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("prefix")
    ap.add_argument("root")
    ap.add_argument("--list", action="store_true",
                    help="only generate the .lst file")
    ap.add_argument("--resize", type=int, default=0)
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--shuffle", action="store_true")
    args = ap.parse_args()
    if args.list or not os.path.exists(args.prefix + ".lst"):
        items = find_images(args.root)
        if args.shuffle:
            random.shuffle(items)
        write_list(args.prefix, items)
        print("wrote %s.lst (%d images)" % (args.prefix, len(items)))
        if args.list:
            return
    entries = read_list(args.prefix)
    make_rec(args.prefix, args.root, entries, args.resize, args.quality)
    print("wrote %s.rec / %s.idx" % (args.prefix, args.prefix))


if __name__ == "__main__":
    main()
