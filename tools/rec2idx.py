#!/usr/bin/env python
"""Create an index file for an existing RecordIO file
(parity: reference tools/rec2idx.py IndexCreator).

The index maps record key -> byte offset so ``MXIndexedRecordIO`` can
random-access records (shuffled epochs, distributed sharding).

Usage:
    python tools/rec2idx.py data.rec data.idx
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu import recordio  # noqa: E402


class IndexCreator(recordio.MXRecordIO):
    """Sequentially read a .rec file, emitting key<TAB>offset per record
    (reference: rec2idx.py IndexCreator — the C-ABI tell() becomes the
    reader's tracked offset)."""

    def __init__(self, idx_path, uri, key_type=int):
        self.fidx = open(idx_path, "w")
        self.key_type = key_type
        super().__init__(uri, "r")

    def close(self):
        if getattr(self, "fidx", None) is not None:
            self.fidx.close()
            self.fidx = None
        super().close()

    def create_index(self):
        """Walk every record; index entry i is the record's byte offset."""
        counter = 0
        while True:
            pos = self.record.tell()  # reader offset (MXRecordIO.tell is writer-only, reference parity)
            cont = self.read()
            if cont is None:
                break
            key = self.key_type(counter)
            self.fidx.write("%s\t%d\n" % (str(key), pos))
            counter += 1
        return counter


def main():
    ap = argparse.ArgumentParser(
        description="Create an index file from a RecordIO file")
    ap.add_argument("record", help="path of the input RecordIO file")
    ap.add_argument("index", help="path of the index file to create")
    args = ap.parse_args()
    creator = IndexCreator(args.index, args.record)
    n = creator.create_index()
    creator.close()
    print("wrote %d index entries -> %s" % (n, args.index))


if __name__ == "__main__":
    main()
