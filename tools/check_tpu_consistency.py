#!/usr/bin/env python
"""Cross-device op consistency sweep: TPU vs CPU oracle.

Parity: the reference's GPU test strategy (SURVEY §4.2) —
``tests/python/gpu/test_operator_gpu.py`` re-runs the CPU op suite on
GPU and ``check_consistency`` (test_utils.py:1422) compares outputs
across devices.  Here the same idea runs against the numerics sweep's
spec table: every op with a sweep spec executes on the TPU and on the
CPU backend, and outputs must agree within dtype-appropriate tolerance.

Run on a machine with a TPU attached:

    python tools/check_tpu_consistency.py [--ops a,b,c] [--tol 2e-2]

The unit suite pins JAX_PLATFORMS=cpu (tests/conftest.py), so this
sweep is the designated way to exercise the op library on real
hardware; the driver's bench covers the model-level path.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "tests"))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", default="",
                    help="comma-separated subset (default: every spec)")
    ap.add_argument("--tol", type=float, default=2e-2,
                    help="max |tpu - cpu| / max(1, |cpu|) allowed")
    ap.add_argument("--grad", action="store_true",
                    help="sweep BACKWARD instead: per-input vjp (ones "
                         "cotangent) TPU vs CPU for every spec the "
                         "numerics suite marks differentiable")
    ap.add_argument("--output", default="")
    args = ap.parse_args()

    import numpy as np
    import jax

    devices = jax.devices()
    accel = [d for d in devices if d.platform != "cpu"]
    if not accel:
        print("no accelerator available; nothing to check", file=sys.stderr)
        return 1
    cpu = jax.devices("cpu")[0]
    dev = accel[0]
    print("comparing %s vs %s" % (dev, cpu), file=sys.stderr)

    import test_op_numerics as sweep  # the sweep's spec table is the input
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.ops import registry

    # coverage gate: every canonical registry op must be swept here or
    # carry a justified exclusion in the sweep module — new ops cannot
    # silently dodge the hardware check (round-3 lesson: the op registry
    # outgrew the sweep without anything noticing).  Enforced only on
    # FULL sweeps: a targeted `--ops foo` debugging run must keep
    # working even while an unrelated coverage gap exists.
    canonical = set(registry._REGISTRY)
    justified = set(sweep.EXCLUDED) | set(sweep._WAVE_EXCLUDED)
    uncovered = sorted(canonical - set(sweep.SPECS) - justified)
    if uncovered and not args.ops:
        print("registry ops with neither a sweep spec nor a justified "
              "exclusion: %s" % ", ".join(uncovered), file=sys.stderr)
        return 3

    names = sorted(sweep.SPECS)
    if args.ops:
        wanted = [n for n in args.ops.split(",") if n]
        unknown = [n for n in wanted if n not in sweep.SPECS]
        if unknown:
            print("unknown ops (no sweep spec): %s" % ", ".join(unknown),
                  file=sys.stderr)
            return 1
        names = wanted
    if args.grad:
        # backward sweep: only specs the numerics suite marks
        # differentiable (those carry FD-checked gradients on CPU; here
        # the same vjp runs on both devices and must agree)
        names = [n for n in names
                 if any(s.grad for s in _as_list(sweep.SPECS[n]))]
    results = {"pass": [], "fail": [], "skip": []}
    run_fn = _run_grad if args.grad else _run
    for name in names:
        if _is_random(name):
            results["skip"].append(name)
            continue
        spec = sweep.SPECS[name]
        specs = spec if isinstance(spec, list) else [spec]
        if args.grad:
            specs = [s for s in specs if s.grad]
        ok = True
        err = 0.0
        try:
            for s in specs:
                outs_t = run_fn(name, s, mx, nd, dev)
                outs_c = run_fn(name, s, mx, nd, cpu)
                if name in _DECOMP and not args.grad:
                    # factorizations are unique only up to sign/rotation:
                    # compare the reconstruction, not the factors
                    outs_t = [_DECOMP[name](outs_t)]
                    outs_c = [_DECOMP[name](outs_c)]
                for a, b in zip(outs_t, outs_c):
                    aa = np.asarray(a, np.float64)
                    bb = np.asarray(b, np.float64)
                    if aa.shape != bb.shape:
                        ok = False
                        break
                    if aa.dtype.kind in "fc":
                        denom = max(1.0, float(np.abs(bb).max()))
                        err = max(err,
                                  float(np.abs(aa - bb).max()) / denom)
                    else:
                        err = max(err, float((aa != bb).any()))
        except Exception as e:  # noqa: BLE001 — report, don't die
            print("ERROR %-40s %s" % (name, e), file=sys.stderr)
            ok = False
        if ok and err <= args.tol:
            results["pass"].append(name)
        else:
            results["fail"].append({"op": name, "err": err})
            print("FAIL %-40s rel err %.3g" % (name, err), file=sys.stderr)
    print("passed %d / failed %d / skipped %d (random)"
          % (len(results["pass"]), len(results["fail"]),
             len(results["skip"])), file=sys.stderr)
    line = json.dumps({
        "metric": "tpu_cpu_grad_consistency" if args.grad
        else "tpu_cpu_op_consistency",
        "platform": dev.platform,
        "passed": len(results["pass"]),
        "failed": len(results["fail"]),
        "skipped_random": len(results["skip"]),
        "registry_canonical": len(canonical),
        "excluded_justified": len(justified),
        "failures": results["fail"][:20],
    })
    print(line)
    if args.output:
        with open(args.output, "w") as f:
            f.write(line)
    return 0 if not results["fail"] else 2


def _svd_rec(outs):
    import numpy as np

    u, sv, vt = (np.asarray(o, np.float64) for o in outs[:3])
    return u @ np.diag(sv) @ vt


def _syevd_rec(outs):
    import numpy as np

    u, lam = (np.asarray(o, np.float64) for o in outs[:2])
    return u.T @ np.diag(lam) @ u


def _gelqf_rec(outs):
    import numpy as np

    l, q = (np.asarray(o, np.float64) for o in outs[:2])
    return l @ q


_DECOMP = {"_npi_svd": _svd_rec, "_linalg_svd": _svd_rec,
           "_linalg_syevd": _syevd_rec, "_linalg_gelqf": _gelqf_rec}


def _is_random(name):
    """RNG-consuming ops (device-dependent draws): exact registry flag,
    not a substring heuristic — gamma/gammaln/_image_normalize are
    deterministic and MUST be swept."""
    from mxnet_tpu.ops import registry

    try:
        return bool(registry.get(name).needs_rng)
    except Exception:
        return False


def _as_list(spec):
    return spec if isinstance(spec, list) else [spec]


def _run_grad(name, spec, mx, nd, device):
    """Per-input gradients (sum-of-outputs loss) with inputs on
    ``device`` — the hardware leg of the suite's FD gradient checks."""
    import jax
    from mxnet_tpu import autograd

    mx.random.seed(7)
    wanted = spec.grad_nodes
    inputs = []
    for i, x in enumerate(spec.inputs):
        arr = nd.array(x)
        arr._set_data(jax.device_put(arr.data(), device))
        # only differentiate the nodes the spec's FD check does —
        # e.g. Embedding indices are not a grad node
        if wanted is None or ("v%d" % i) in wanted:
            arr.attach_grad()
        inputs.append(arr)
    fn = getattr(mx.nd, name, None)
    if fn is None:
        from mxnet_tpu.ndarray.register import make_op_func

        fn = make_op_func(name)
    with autograd.record():
        out = fn(*inputs, **spec.attrs)
        outs = out if isinstance(out, list) else [out]
        loss = outs[0].sum()
        for o in outs[1:]:
            loss = loss + o.sum()
    loss.backward()
    return [arr.grad.asnumpy() for arr in inputs
            if arr.grad is not None]


def _run(name, spec, mx, nd, device):
    """Execute one spec's forward with inputs placed on ``device``."""
    import jax

    mx.random.seed(7)
    inputs = []
    for x in spec.inputs:
        arr = nd.array(x)
        arr._set_data(jax.device_put(arr.data(), device))
        inputs.append(arr)
    fn = getattr(mx.nd, name, None)
    if fn is None:
        from mxnet_tpu.ndarray.register import make_op_func

        fn = make_op_func(name)
    out = fn(*inputs, **spec.attrs)
    outs = out if isinstance(out, list) else [out]
    return [o.asnumpy() for o in outs]


if __name__ == "__main__":
    sys.exit(main())
