#!/usr/bin/env python
"""Cluster launcher (parity: reference tools/launch.py:28-50 + the dmlc
tracker backends local/ssh/mpi).

Spawns S server processes and N worker processes with the reference's
DMLC_* environment contract and a per-job HMAC secret, runs the given
command in each worker, and waits.  Exit status is non-zero if any worker
fails.

Launchers:

``local``
    everything on this machine (subprocesses).
``ssh``
    workers round-robin over the hosts in ``-H hostfile`` via ssh; the
    parameter servers run on the launcher host (workers connect back to
    ``--root-uri``, which must be this machine's address as seen from the
    workers).  Environment (the DMLC_*/MXNET_* job contract plus ``--env``
    names) is exported explicitly in the remote command — ssh does not
    forward env.  ``--sync-dst-dir`` rsyncs the current directory to every
    host first (reference dmlc_tracker/ssh.py behaviour).
``mpi``
    one ``mpirun`` invocation per role with ``-x`` env forwarding (OpenMPI
    convention); host placement is mpirun's, via ``-H``/hostfile args in
    ``--mpi-args``.

Usage:
    python tools/launch.py -n 2 [-s 1] [--kv-store dist_sync] python train.py
    python tools/launch.py -n 4 --launcher ssh -H hosts.txt \
        --root-uri 10.0.0.1 python train.py
"""
from __future__ import annotations

import argparse
import os
import shlex
import socket
import subprocess
import sys
import time


def _free_port():
    s = socket.socket()
    try:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
    finally:
        s.close()


def _default_root_uri():
    """An address of this host that remote workers can reach."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 53))  # no traffic; just picks the route
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return socket.gethostbyname(socket.gethostname())


# env vars exported through ssh (the job contract + backend selection);
# --env appends to this
_JOB_ENV_NAMES = (
    "DMLC_PS_ROOT_URI", "DMLC_PS_ROOT_PORT", "DMLC_NUM_WORKER",
    "DMLC_NUM_SERVER", "DMLC_ROLE", "DMLC_RANK", "DMLC_WORKER_ID",
    "DMLC_SERVER_ID", "MXNET_KVSTORE_MODE", "MXNET_KVSTORE_SECRET",
    "MXNET_KVSTORE_TIMEOUT", "JAX_PLATFORMS", "PYTHONPATH",
)


def _remote_command(env, command, workdir, env_names):
    """One shell line: exports + cd + command (dmlc ssh.py's pass_envs).

    Fed to the remote shell over STDIN (``ssh host /bin/sh -s``), never as
    an argv element: the line carries MXNET_KVSTORE_SECRET, and argv is
    world-readable in the process list on both ends.
    """
    parts = []
    for name in env_names:
        if name in env:
            parts.append("export %s=%s" % (name, shlex.quote(env[name])))
    parts.append("cd %s" % shlex.quote(workdir))
    parts.append(" ".join(shlex.quote(c) for c in command))
    return "; ".join(parts)


def _read_hostfile(path):
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                # accept 'host', 'host slots=N' and MPI-style 'host:N'
                hosts.append(line.split()[0].split(":")[0])
    if not hosts:
        raise ValueError("hostfile %s has no hosts" % path)
    return hosts


def _sync_dir(hosts, src, dst, ssh_bin):
    rsh = ssh_bin if ssh_bin != "ssh" else None
    for h in hosts:
        cmd = ["rsync", "-az", "--exclude", ".git",
               src + "/", "%s:%s/" % (h, dst)]
        if rsh:
            cmd[1:1] = ["-e", rsh]
        subprocess.check_call(cmd)


def launch(num_workers, num_servers, command, kv_store="dist_sync",
           env_extra=None, launcher="local", hosts=None, ssh_bin="ssh",
           root_uri=None, env_names=(), workdir=None, sync_dst_dir=None,
           mpi_args=(), log_dir=None, backend="ps"):
    import secrets

    log_handles = []

    def _role_out(role, i):
        if not log_dir:
            return None
        os.makedirs(log_dir, exist_ok=True)
        fh = open(os.path.join(log_dir, "%s_%d.log" % (role, i)), "wb")
        log_handles.append(fh)
        return fh

    root_port = _free_port()
    explicit_uri = root_uri is not None
    if launcher == "local":
        root_uri = "127.0.0.1"
    elif root_uri is None:
        root_uri = _default_root_uri()
    base_env = dict(os.environ)
    base_env.update({
        "DMLC_PS_ROOT_URI": root_uri,
        "DMLC_PS_ROOT_PORT": str(root_port),
        "DMLC_NUM_WORKER": str(num_workers),
        "DMLC_NUM_SERVER": str(num_servers),
        "MXNET_KVSTORE_MODE": kv_store,
        # per-job shared secret for the typed-wire HMAC handshake
        "MXNET_KVSTORE_SECRET": base_env.get("MXNET_KVSTORE_SECRET")
        or secrets.token_hex(16),
    })
    base_env.update(env_extra or {})
    all_env_names = tuple(_JOB_ENV_NAMES) + tuple(env_names)
    workdir = workdir or os.getcwd()

    if launcher == "ssh":
        if not hosts:
            raise ValueError("--launcher ssh needs a hostfile (-H)")
        if sync_dst_dir:
            _sync_dir(hosts, workdir, sync_dst_dir, ssh_bin)
            workdir = sync_dst_dir
    elif launcher not in ("local", "mpi"):
        raise ValueError("unknown launcher %r" % launcher)

    if backend == "gspmd":
        # GSPMD tier: no parameter servers — workers join ONE logical XLA
        # program via jax.distributed (parallel/multihost.py); the DMLC
        # root URI/port doubles as the coordinator address.  The
        # coordinator SERVICE runs inside rank 0's process, so over ssh
        # the address must be rank 0's HOST (hosts[0]), not the launcher
        # (and the port only needs to be free there — a fixed high port
        # beats a launcher-local _free_port probe)
        num_servers = 0
        if launcher == "ssh" and hosts and not explicit_uri:
            base_env["DMLC_PS_ROOT_URI"] = hosts[0].split(":")[0]

    # parameter servers always run on the launcher host: workers connect
    # back to (root_uri, root_port+1+sid).  ps-lite servers never touch
    # the accelerator; neither do these (host CPU processes).
    server_cmd = [sys.executable, "-c",
                  "from mxnet_tpu.kvstore.kvstore_server import "
                  "KVStoreServer; KVStoreServer().run()"]
    procs = []
    for sid in range(num_servers):
        env = dict(base_env)
        env.update({"DMLC_ROLE": "server", "DMLC_SERVER_ID": str(sid)})
        env["JAX_PLATFORMS"] = (env_extra or {}).get("JAX_PLATFORMS", "cpu")
        out = _role_out("server", sid)
        procs.append(subprocess.Popen(server_cmd, env=env,
                                      stdout=out, stderr=out))
    time.sleep(0.5)  # workers ALSO retry refused connects (dist_kvstore)

    workers = []
    for rank in range(num_workers):
        env = dict(base_env)
        env.update({"DMLC_ROLE": "worker", "DMLC_RANK": str(rank),
                    "DMLC_WORKER_ID": str(rank)})
        wout = _role_out("worker", rank)
        if launcher == "ssh":
            host = hosts[rank % len(hosts)]
            line = _remote_command(env, command, workdir, all_env_names)
            p = subprocess.Popen(
                shlex.split(ssh_bin) + [host, "/bin/sh -s"],
                env=env, stdin=subprocess.PIPE, stdout=wout, stderr=wout)
            p.stdin.write(line.encode())
            p.stdin.close()
            workers.append(p)
        elif launcher == "mpi":
            cmd = ["mpirun", "-n", "1"] + list(mpi_args)
            for name in all_env_names:
                if name in env:
                    cmd += ["-x", "%s=%s" % (name, env[name])]
            workers.append(subprocess.Popen(cmd + list(command), env=env,
                                            stdout=wout, stderr=wout))
        else:
            workers.append(subprocess.Popen(command, env=env,
                                            stdout=wout, stderr=wout))

    rc = 0
    for w in workers:
        rc |= w.wait()
    for p in procs:
        p.terminate()
    for p in procs:
        try:
            p.wait(timeout=5)
        except subprocess.TimeoutExpired:
            p.kill()
    for fh in log_handles:
        fh.close()
    return rc


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, default=1)
    ap.add_argument("--kv-store", default="dist_sync")
    ap.add_argument("--launcher", default="local",
                    choices=["local", "ssh", "mpi"],
                    help="local subprocesses, ssh over a hostfile, or one "
                         "mpirun per worker (sge/yarn: submit this script "
                         "with --launcher local per allocation)")
    ap.add_argument("-H", "--hostfile",
                    help="hosts file for --launcher ssh (one host per line)")
    ap.add_argument("--root-uri",
                    help="address of THIS host reachable from the workers "
                         "(default: auto-detected primary address)")
    ap.add_argument("--ssh-bin", default="ssh",
                    help="ssh command (override for tests / alternative "
                         "transports)")
    ap.add_argument("--sync-dst-dir",
                    help="rsync the current directory to this path on every "
                         "host before launching (reference --sync-dst-dir)")
    ap.add_argument("--env", action="append", default=[],
                    help="extra env var NAMES to propagate to remote "
                         "workers (values taken from this environment)")
    ap.add_argument("--backend", default="ps", choices=["ps", "gspmd"],
                    help="ps: parameter-server tier (dist kvstore); "
                         "gspmd: one logical XLA program over all hosts "
                         "(jax.distributed rendezvous, no servers)")
    ap.add_argument("--log-dir",
                    help="redirect each server/worker's stdout+stderr to "
                         "<log-dir>/<role>_<i>.log")
    ap.add_argument("--mpi-args", default="",
                    help="extra args spliced into each mpirun invocation")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if not args.command:
        ap.error("no command given")
    hosts = _read_hostfile(args.hostfile) if args.hostfile else None
    sys.exit(launch(
        args.num_workers, args.num_servers, args.command,
        kv_store=args.kv_store, launcher=args.launcher, hosts=hosts,
        ssh_bin=args.ssh_bin, root_uri=args.root_uri,
        env_names=tuple(args.env), sync_dst_dir=args.sync_dst_dir,
        mpi_args=tuple(shlex.split(args.mpi_args)), log_dir=args.log_dir,
        backend=args.backend))


if __name__ == "__main__":
    main()
