#!/usr/bin/env python
"""Local cluster launcher (parity: reference tools/launch.py:28 with the
dmlc "local" tracker).

Spawns S server processes and N worker processes on this machine with the
reference's DMLC_* environment contract, runs the given command in each
worker, and waits.  Exit status is non-zero if any worker fails.

Usage:
    python tools/launch.py -n 2 [-s 1] [--kv-store dist_sync] python train.py
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import time


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def launch(num_workers, num_servers, command, kv_store="dist_sync",
           env_extra=None):
    import secrets

    root_port = _free_port()
    base_env = dict(os.environ)
    base_env.update({
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(root_port),
        "DMLC_NUM_WORKER": str(num_workers),
        "DMLC_NUM_SERVER": str(num_servers),
        "MXNET_KVSTORE_MODE": kv_store,
        # per-job shared secret for the typed-wire HMAC handshake
        "MXNET_KVSTORE_SECRET": base_env.get("MXNET_KVSTORE_SECRET")
        or secrets.token_hex(16),
    })
    base_env.update(env_extra or {})

    procs = []
    for sid in range(num_servers):
        env = dict(base_env)
        env.update({"DMLC_ROLE": "server", "DMLC_SERVER_ID": str(sid)})
        # servers are CPU processes (parity: ps-lite servers never touch
        # the accelerator) — and must not wedge on accelerator backend
        # init when the device link is down
        env["JAX_PLATFORMS"] = (env_extra or {}).get("JAX_PLATFORMS", "cpu")
        procs.append(subprocess.Popen(
            [sys.executable, "-c",
             "from mxnet_tpu.kvstore.kvstore_server import KVStoreServer;"
             "KVStoreServer().run()"],
            env=env))
    time.sleep(0.5)  # let servers bind before workers connect

    workers = []
    for rank in range(num_workers):
        env = dict(base_env)
        env.update({"DMLC_ROLE": "worker", "DMLC_RANK": str(rank),
                    "DMLC_WORKER_ID": str(rank)})
        workers.append(subprocess.Popen(command, env=env))

    rc = 0
    for w in workers:
        rc |= w.wait()
    for p in procs:
        p.terminate()
    for p in procs:
        try:
            p.wait(timeout=5)
        except subprocess.TimeoutExpired:
            p.kill()
    return rc


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, default=1)
    ap.add_argument("--kv-store", default="dist_sync")
    ap.add_argument("--launcher", default="local",
                    help="only 'local' is implemented (ssh/mpi/yarn: use "
                         "your scheduler to run this per host)")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if args.launcher != "local":
        ap.error("only --launcher local is implemented")
    if not args.command:
        ap.error("no command given")
    sys.exit(launch(args.num_workers, args.num_servers, args.command,
                    kv_store=args.kv_store))


if __name__ == "__main__":
    main()
