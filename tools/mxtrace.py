#!/usr/bin/env python
"""mxtrace: work with mxnet_tpu profiler traces from the command line.

Subcommands:

  merge   Merge per-process chrome-trace dumps (workers + servers) into
          ONE chrome://tracing file on a correlated timeline::

              python tools/mxtrace.py merge worker0.json worker1.json \\
                  server.json -o merged.json --labels worker0 worker1 srv

          Timelines are aligned via the wall-clock anchor every dump
          carries (otherData.wall_t0_us); server handler spans keep
          their pid (= requesting rank + 1) while each input's local
          events get a fresh pid.  Load the result in chrome://tracing
          or https://ui.perfetto.dev — a worker's kv_push span sits
          directly over the server handler span it triggered (both
          carry the same args.span id).  See docs/observability.md.

          Flight-recorder dumps are accepted in place (auto-detected by
          their ``meta``/``events`` shape and converted via
          ``flight.to_trace``), so one command lines the fleet router's
          attempt spans up against each replica's serve timeline::

              python tools/mxtrace.py merge router_flight.json \\
                  replica0_flight.json -o fleet.json --labels router r0

  summary Per-op aggregate table (count/total/avg/min/max us) from one
          or more trace files, like ``mx.profiler.dumps()`` but offline.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)


def _cmd_merge(args):
    from mxnet_tpu.telemetry import flight, merge_traces

    inputs = []
    for path in args.traces:
        with open(path) as f:
            doc = json.load(f)
        if isinstance(doc, dict) and "meta" in doc and "events" in doc:
            # a flight-recorder dump, not a chrome trace: convert it —
            # router fleet.attempt/fleet.request events become spans on
            # per-replica rows, so one `mxtrace merge router.json
            # replica0.json replica1.json` shows a hedged request
            # spanning two replicas next to each replica's own timeline
            inputs.append(flight.to_trace(flight.load(path)))
        else:
            inputs.append(path)
    merged = merge_traces(inputs, out=args.output, labels=args.labels)
    n = sum(1 for e in merged["traceEvents"] if e.get("ph") != "M")
    print("merged %d events from %d trace(s) -> %s"
          % (n, len(args.traces), args.output))
    return 0


def _cmd_summary(args):
    stats = {}
    for path in args.traces:
        with open(path) as f:
            trace = json.load(f)
        events = trace.get("traceEvents", trace) \
            if isinstance(trace, dict) else trace
        for e in events:
            if e.get("ph") != "X":
                continue
            s = stats.setdefault(e["name"], {"count": 0, "total": 0.0,
                                             "min": float("inf"),
                                             "max": 0.0})
            s["count"] += 1
            s["total"] += e["dur"]
            s["min"] = min(s["min"], e["dur"])
            s["max"] = max(s["max"], e["dur"])
    rows = sorted(stats.items(), key=lambda kv: kv[1]["total"],
                  reverse=True)
    print("%-40s %8s %12s %12s %12s %12s"
          % ("Name", "Calls", "Total(us)", "Avg(us)", "Min(us)",
             "Max(us)"))
    for name, s in rows:
        print("%-40s %8d %12.1f %12.1f %12.1f %12.1f"
              % (name[:40], s["count"], s["total"],
                 s["total"] / s["count"], s["min"], s["max"]))
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="mxtrace", description=__doc__,
                                 formatter_class=argparse.
                                 RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="command", required=True)

    mp = sub.add_parser("merge", help="merge per-process traces")
    mp.add_argument("traces", nargs="+", help="chrome-trace JSON files")
    mp.add_argument("-o", "--output", default="merged_trace.json")
    mp.add_argument("--labels", nargs="*", default=None,
                    help="display name per input (default worker<i>)")
    mp.set_defaults(fn=_cmd_merge)

    sp = sub.add_parser("summary", help="per-op aggregate table")
    sp.add_argument("traces", nargs="+")
    sp.set_defaults(fn=_cmd_summary)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
