#!/usr/bin/env python
"""Parse training logs into a metric table (parity: tools/parse_log.py).

Understands the reference's log line shapes::

    Epoch[3] Batch [200]  Speed: 1234.5 samples/sec  accuracy=0.91
    Epoch[3] Validation-accuracy=0.89
    Epoch[3] Time cost=12.3

Usage: python tools/parse_log.py LOGFILE [--format markdown|csv]
"""
from __future__ import annotations

import argparse
import re
import sys

_EPOCH = re.compile(r"Epoch\[(\d+)\]")
_SPEED = re.compile(r"Speed:\s*([\d.]+)")
_METRIC = re.compile(r"(\S+?)=([\d.eE+-]+)")
_TIME = re.compile(r"Time cost=([\d.]+)")


def parse(lines):
    epochs = {}
    for line in lines:
        m = _EPOCH.search(line)
        if not m:
            continue
        ep = int(m.group(1))
        rec = epochs.setdefault(ep, {"speeds": []})
        sp = _SPEED.search(line)
        if sp:
            rec["speeds"].append(float(sp.group(1)))
        t = _TIME.search(line)
        if t:
            rec["time"] = float(t.group(1))
        for name, val in _METRIC.findall(line):
            if name.lower().startswith(("speed", "time")):
                continue
            rec[name] = float(val)
    return epochs


def render(epochs, fmt="markdown"):
    cols = sorted({k for rec in epochs.values() for k in rec
                   if k != "speeds"})
    header = ["epoch", "speed(avg)"] + cols
    rows = []
    for ep in sorted(epochs):
        rec = epochs[ep]
        speed = (sum(rec["speeds"]) / len(rec["speeds"])
                 if rec["speeds"] else float("nan"))
        rows.append([str(ep), "%.1f" % speed]
                    + ["%.6g" % rec.get(c, float("nan")) for c in cols])
    if fmt == "csv":
        return "\n".join(",".join(r) for r in [header] + rows)
    out = ["| " + " | ".join(header) + " |",
           "|" + "---|" * len(header)]
    out += ["| " + " | ".join(r) + " |" for r in rows]
    return "\n".join(out)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("logfile")
    ap.add_argument("--format", default="markdown",
                    choices=("markdown", "csv"))
    args = ap.parse_args()
    with open(args.logfile) as f:
        print(render(parse(f), args.format))


if __name__ == "__main__":
    main()
