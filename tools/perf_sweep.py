#!/usr/bin/env python
"""ResNet-50 train-throughput sweep: batch size × layout × XLA flag sets.

The round-3 verdict's open perf item (VERDICT.md "What's weak" #3): the
~2.1k img/s chip number was attributed to XLA's conv kernels, but no
attempt was recorded to *move* the ceiling.  This tool is that attempt,
kept in-tree so the study is reproducible: every configuration runs in a
fresh subprocess (XLA flags only take effect before backend init) and
reports one line; the parent prints a table plus the winner.

Usage (on a machine with the chip attached):

    python tools/perf_sweep.py                 # default grid
    python tools/perf_sweep.py --quick         # 3-point sanity grid
    python tools/perf_sweep.py --flags-only    # hold batch fixed, sweep flags

Each child measures the same fused train step bench.py measures (10
device-side steps via JitTrainStep.step_n, donated buffers, bf16 AMP).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import json, os, sys, time
import numpy as np

sys.path.insert(0, %(root)r)
import jax, jax.numpy as jnp

cfg = json.loads(os.environ["SWEEP_CFG"])
try:
    cache = os.path.join(%(root)r, ".jax_cache")
    os.makedirs(cache, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
except Exception:
    pass

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from mxnet_tpu.gluon.model_zoo import vision

if cfg.get("layout"):
    mx.set_default_layout(cfg["layout"])
mx.random.seed(0)
net = vision.resnet50_v1()
net.initialize(mx.init.Xavier())
from mxnet_tpu import amp
amp.init("bfloat16")
amp.convert_hybrid_block(net)
step = parallel.JitTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             "sgd", {"learning_rate": 0.1, "momentum": 0.9})
batch = cfg["batch"]
x = np.random.RandomState(0).rand(batch, 3, 224, 224).astype(np.float32)
x = jnp.asarray(x, jnp.bfloat16)
y = np.random.RandomState(0).randint(0, 1000, batch).astype(np.float32)
n = 10
loss = step.step_n(n, x, y)          # compile + warm
jax.block_until_ready(loss)
loss = step.step_n(n, x, y)
jax.block_until_ready(loss)
t0 = time.perf_counter()
loss = step.step_n(n, x, y)
jax.block_until_ready(loss)
dt = time.perf_counter() - t0
print("RESULT " + json.dumps({"img_s": round(batch * n / dt, 1),
                              "loss": float(loss)}))
"""


def run_cfg(batch, layout=None, xla_flags="", timeout=900):
    env = dict(os.environ)
    env["SWEEP_CFG"] = json.dumps({"batch": batch, "layout": layout})
    base = env.get("XLA_FLAGS", "")
    if xla_flags:
        env["XLA_FLAGS"] = (base + " " + xla_flags).strip()
    try:
        out = subprocess.run(
            [sys.executable, "-c", _CHILD % {"root": _ROOT}],
            capture_output=True, text=True, timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        return {"error": "timeout"}
    for line in out.stdout.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    err = (out.stderr or "").strip().splitlines()
    tail = err[-1][-160:] if err else "no output"
    if "RESOURCE_EXHAUSTED" in (out.stderr or ""):
        tail = "OOM"
    return {"error": tail}


# flag sets worth trying on this jaxlib; unknown flags make XLA abort, so
# each runs isolated and a failure is just reported
FLAG_SETS = {
    "base": "",
    "latency-sched": "--xla_tpu_enable_latency_hiding_scheduler=true",
    "async-all": ("--xla_tpu_enable_latency_hiding_scheduler=true "
                  "--xla_enable_async_all_gather=true "
                  "--xla_enable_async_collective_permute=true"),
    "broadcast-priority": "--xla_tpu_enable_aggressive_broadcast_priority_update=true",
    "flash-fusion": "--xla_tpu_enable_flash_attention=true",
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--flags-only", action="store_true")
    ap.add_argument("--batches", default="")
    args = ap.parse_args()

    results = []
    if args.batches:
        batches = [int(b) for b in args.batches.split(",")]
    elif args.quick:
        batches = [128]
    else:
        batches = [128, 192, 256, 384, 512]

    if not args.flags_only:
        for layout in (None, "NCHW", "NHWC"):
            for b in batches:
                r = run_cfg(b, layout=layout)
                row = {"batch": b, "layout": layout or "auto",
                       "flags": "base", **r}
                results.append(row)
                print(json.dumps(row), flush=True)

    best_batch = max((r for r in results if "img_s" in r),
                     key=lambda r: r["img_s"], default=None)
    # no batch sweep ran (--flags-only) or all failed: use the measured
    # sweet spot (384, docs/perf.md), not the largest/near-OOM batch
    fb = best_batch["batch"] if best_batch else 384
    fl = None if not best_batch or best_batch["layout"] == "auto" \
        else best_batch["layout"]
    for name, flags in FLAG_SETS.items():
        if name == "base" and not args.flags_only:
            continue
        r = run_cfg(fb, layout=fl, xla_flags=flags)
        row = {"batch": fb, "layout": fl or "auto", "flags": name, **r}
        results.append(row)
        print(json.dumps(row), flush=True)

    ok = [r for r in results if "img_s" in r]
    if ok:
        best = max(ok, key=lambda r: r["img_s"])
        print("BEST " + json.dumps(best))


if __name__ == "__main__":
    main()
