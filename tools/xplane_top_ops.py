"""Print top self-time ops from a jax profiler xplane capture.

Parses the XSpace proto directly (no tensorboard needed):
aggregates XEvent durations per HLO op name on the device plane.

Usage: python tools/xplane_top_ops.py /tmp/rn50_trace [N]
"""
import glob
import sys
from collections import defaultdict


def main():
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    logdir = sys.argv[1]
    topn = int(sys.argv[2]) if len(sys.argv) > 2 else 40
    paths = glob.glob(logdir + "/**/*.xplane.pb", recursive=True)
    assert paths, "no xplane under %s" % logdir
    space = xplane_pb2.XSpace()
    with open(sorted(paths)[-1], "rb") as f:
        space.ParseFromString(f.read())

    for plane in space.planes:
        if "TPU" not in plane.name and "device" not in plane.name.lower():
            continue
        ev_meta = {m.id: m.name for m in plane.event_metadata.values()}
        totals = defaultdict(float)
        counts = defaultdict(int)
        grand = 0.0
        for line in plane.lines:
            # XLA op lines carry per-op events; step lines we skip
            for ev in line.events:
                name = ev_meta.get(ev.metadata_id, "?")
                dur = ev.duration_ps / 1e12
                totals[(line.name, name)] += dur
                counts[(line.name, name)] += 1
        by_line = defaultdict(float)
        for (ln, name), d in totals.items():
            by_line[ln] += d
        print("== plane:", plane.name)
        for ln, d in sorted(by_line.items(), key=lambda kv: -kv[1]):
            print("  line %-28s total %.4fs" % (ln, d))
        # per-op tables for every op line (async copies overlap compute,
        # so the busiest line by wall-sum is often NOT where step time
        # goes — print both and let the reader compare)
        for ln in sorted(by_line, key=by_line.get, reverse=True):
            if ln in ("Steps", "XLA Modules"):
                continue
            print("-- top ops on line %r --" % ln)
            items = [(n, d, counts[(ln2, n)])
                     for (ln2, n), d in totals.items() if ln2 == ln]
            tot = sum(d for _, d, _ in items) or 1.0
            for n, d, c in sorted(items, key=lambda kv: -kv[1])[:topn]:
                print("  %6.2f%% %9.4fs x%-5d %s"
                      % (100 * d / tot, d, c, n[:110]))


if __name__ == "__main__":
    main()
