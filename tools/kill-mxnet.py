#!/usr/bin/env python
"""Kill stray training processes on this machine
(parity: reference tools/kill-mxnet.py, which pkilled the python
processes of a dmlc job).

Matches python processes whose command line mentions the given program
name (default: any mxnet_tpu entrypoint) and SIGTERMs them, escalating
to SIGKILL after a grace period.

Usage: python tools/kill-mxnet.py [prog_name]
"""
from __future__ import annotations

import os
import signal
import sys
import time


def _ancestors():
    """PIDs of this process's ancestor chain (never kill those — their
    command lines quote OUR argv, including the search needle)."""
    chain, pid = set(), os.getpid()
    while pid > 1:
        chain.add(pid)
        try:
            with open("/proc/%d/stat" % pid) as f:
                pid = int(f.read().rsplit(")", 1)[1].split()[1])
        except (OSError, IndexError, ValueError):
            break
    chain.add(1)
    return chain


def find_procs(needle):
    skip = _ancestors()
    out = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit() or int(pid) in skip:
            continue
        try:
            with open("/proc/%s/cmdline" % pid, "rb") as f:
                cmd = f.read().replace(b"\x00", b" ").decode(
                    "utf-8", "replace")
        except OSError:
            continue
        if "python" in cmd and needle in cmd:
            out.append((int(pid), cmd.strip()))
    return out


def main():
    # default: anything running code from THIS repo (the package name
    # rarely appears on the command line; the repo path does)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    needle = sys.argv[1] if len(sys.argv) > 1 else repo
    procs = find_procs(needle)
    if not procs:
        print("no matching processes for %r" % needle)
        return
    for pid, cmd in procs:
        print("SIGTERM %d: %s" % (pid, cmd[:100]))
        try:
            os.kill(pid, signal.SIGTERM)
        except OSError:
            pass
    time.sleep(2)
    # re-match before escalating: the PID may have been recycled for an
    # unrelated process during the grace period
    still = {pid for pid, _ in find_procs(needle)}
    for pid, _cmd in procs:
        if pid in still:
            print("SIGKILL %d (did not exit)" % pid)
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass


if __name__ == "__main__":
    main()
