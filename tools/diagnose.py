#!/usr/bin/env python
"""Diagnose the runtime environment
(parity: reference tools/diagnose.py — python/pip/OS/hardware/framework
checks; the network-reachability checks become backend/device checks,
since the TPU build's critical dependency is the XLA backend, not a
download mirror).

Usage: python tools/diagnose.py
"""
from __future__ import annotations

import os
import platform
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def check_python():
    print("----------Python Info----------")
    print("Version      :", platform.python_version())
    print("Compiler     :", platform.python_compiler())
    print("Build        :", platform.python_build())
    print("Arch         :", platform.architecture())


def check_pip():
    print("------------Pip Info-----------")
    try:
        import pip

        print("Version      :", pip.__version__)
    except ImportError:
        print("No corresponding pip install for current python.")


def check_mxnet():
    print("----------MXNet-TPU Info-----------")
    t0 = time.time()
    try:
        import mxnet_tpu as mx

        print("Imported in  : %.2fs" % (time.time() - t0))
        print("Directory    :", os.path.dirname(mx.__file__))
        from mxnet_tpu.runtime import Features

        feats = Features()
        on = [k for k in feats.keys() if feats.is_enabled(k)]
        print("Features     :", ", ".join(on) if on else "(none)")
    except Exception as e:  # keep diagnosing even on failure
        print("mxnet_tpu import FAILED:", e)


def check_backend():
    print("----------Backend Info---------")
    try:
        import jax

        print("jax          :", jax.__version__)
        t0 = time.time()
        devs = jax.devices()
        print("Devices      : %s (init %.2fs)" % (devs, time.time() - t0))
        print("Default      :", jax.default_backend())
    except Exception as e:
        print("jax backend FAILED:", e)


def check_os():
    print("----------System Info----------")
    print("Platform     :", platform.platform())
    print("system       :", platform.system())
    print("node         :", platform.node())
    print("release      :", platform.release())
    print("version      :", platform.version())


def check_hardware():
    print("----------Hardware Info----------")
    print("machine      :", platform.machine())
    print("processor    :", platform.processor())
    if sys.platform.startswith("linux"):
        try:
            out = subprocess.run(["lscpu"], capture_output=True,
                                 text=True, timeout=10).stdout
            for line in out.splitlines():
                if any(k in line for k in ("Model name", "CPU(s)",
                                           "Thread", "Socket")):
                    print(line)
        except Exception:
            pass


def check_environment():
    print("----------Environment----------")
    for k, v in sorted(os.environ.items()):
        if k.startswith(("MXNET_", "JAX_", "XLA_", "DMLC_", "OMP_")):
            if "SECRET" in k:
                v = "<redacted>"
            print("%s=\"%s\"" % (k, v))


if __name__ == "__main__":
    check_python()
    check_pip()
    check_mxnet()
    check_backend()
    check_os()
    check_hardware()
    check_environment()
