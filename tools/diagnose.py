#!/usr/bin/env python
"""Diagnose the runtime environment
(parity: reference tools/diagnose.py — python/pip/OS/hardware/framework
checks; the network-reachability checks become backend/device checks,
since the TPU build's critical dependency is the XLA backend, not a
download mirror).

Usage: python tools/diagnose.py
"""
from __future__ import annotations

import os
import platform
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def check_python():
    print("----------Python Info----------")
    print("Version      :", platform.python_version())
    print("Compiler     :", platform.python_compiler())
    print("Build        :", platform.python_build())
    print("Arch         :", platform.architecture())


def check_pip():
    print("------------Pip Info-----------")
    try:
        import pip

        print("Version      :", pip.__version__)
    except ImportError:
        print("No corresponding pip install for current python.")


def check_mxnet():
    print("----------MXNet-TPU Info-----------")
    t0 = time.time()
    try:
        import mxnet_tpu as mx

        print("Imported in  : %.2fs" % (time.time() - t0))
        print("Directory    :", os.path.dirname(mx.__file__))
        from mxnet_tpu.runtime import Features

        feats = Features()
        on = [k for k in feats.keys() if feats.is_enabled(k)]
        print("Features     :", ", ".join(on) if on else "(none)")
    except Exception as e:  # keep diagnosing even on failure
        print("mxnet_tpu import FAILED:", e)


def check_backend():
    """Backend init can HANG (not raise) when the accelerator link is
    down, so the device query runs under a watchdog and reports a
    timeout instead of wedging the whole diagnostic (which would defeat
    its purpose exactly when it is most needed)."""
    print("----------Backend Info---------")
    try:
        import threading

        import jax

        # honor a JAX_PLATFORMS env override even if the image pinned a
        # platform through the config API at interpreter startup
        try:
            # the package's import-time guard applies the canonical
            # rule (mxnet_tpu.__init__._platform_override_needed);
            # importing does not initialize a backend
            import mxnet_tpu  # noqa: F401
        except Exception:
            pass

        print("jax          :", jax.__version__)
        t0 = time.time()
        res = {}
        done = threading.Event()

        def _probe():
            try:
                res["devs"] = jax.devices()
            except Exception as e:  # noqa: BLE001
                res["err"] = e
            done.set()

        threading.Thread(target=_probe, daemon=True).start()
        budget = float(os.environ.get("MXNET_DIAGNOSE_TIMEOUT", "60"))
        if not done.wait(timeout=budget):
            print("Devices      : TIMED OUT after %.0fs — backend init is "
                  "wedged (accelerator tunnel down?)" % budget)
            return
        if "err" in res:
            print("Devices      : init FAILED:", res["err"])
            return
        devs = res["devs"]
        print("Devices      : %s (init %.2fs)" % (devs, time.time() - t0))
        print("Default      :", jax.default_backend())
    except Exception as e:
        print("jax backend FAILED:", e)


def check_os():
    print("----------System Info----------")
    print("Platform     :", platform.platform())
    print("system       :", platform.system())
    print("node         :", platform.node())
    print("release      :", platform.release())
    print("version      :", platform.version())


def check_hardware():
    print("----------Hardware Info----------")
    print("machine      :", platform.machine())
    print("processor    :", platform.processor())
    if sys.platform.startswith("linux"):
        try:
            out = subprocess.run(["lscpu"], capture_output=True,
                                 text=True, timeout=10).stdout
            for line in out.splitlines():
                if any(k in line for k in ("Model name", "CPU(s)",
                                           "Thread", "Socket")):
                    print(line)
        except Exception:
            pass


def check_environment():
    print("----------Environment----------")
    for k, v in sorted(os.environ.items()):
        if k.startswith(("MXNET_", "JAX_", "XLA_", "DMLC_", "OMP_")):
            if "SECRET" in k:
                v = "<redacted>"
            print("%s=\"%s\"" % (k, v))


if __name__ == "__main__":
    check_python()
    check_pip()
    check_mxnet()
    check_backend()
    check_os()
    check_hardware()
    check_environment()
