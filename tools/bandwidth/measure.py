#!/usr/bin/env python
"""All-reduce bandwidth microbenchmark.

Parity: reference ``tools/bandwidth/measure.py`` (KVStore allreduce
bandwidth; its README reports ~4.5 GB/s/GPU over PCIe at 8 GPUs).
Here the collective is an XLA ``psum`` over the device mesh — ICI on a
real pod, shared-memory on the virtual CPU mesh — which is the rebuild's
actual gradient-aggregation path (compiled into the train step).

``--dist`` instead measures the DCN tier: push+pull round-trip
throughput of the typed dist-kvstore wire against an in-process
DistServer over loopback TCP (upper bound of the protocol + framing
stack; real DCN adds the network itself).

Usage:
    python tools/bandwidth/measure.py [--size-mb 64] [--runs 10]
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/bandwidth/measure.py   # 8 virtual devices
    python tools/bandwidth/measure.py --dist  # dist-kvstore TCP wire
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))


def measure_dist(size_mb, runs):
    """Loopback push+pull throughput of the typed dist-kvstore wire.

    The server runs in a SUBPROCESS: an in-process server thread shares
    the GIL and the measurement then reports Python contention, not the
    protocol (measured ~0.6 GB/s in-process vs the subprocess number).
    """
    import subprocess
    import time as _t

    import numpy as np

    from mxnet_tpu import nd
    from mxnet_tpu.parallel.dist_kvstore import (
        DistKVStore, _server_port)

    root_port = 23450
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    server = subprocess.Popen(
        [sys.executable, "-c",
         "import sys; sys.path.insert(0, %r)\n"
         "from mxnet_tpu.parallel.dist_kvstore import DistServer, _server_port\n"
         "DistServer(_server_port(%d, 0), num_workers=1, sync=True).run()\n"
         % (os.path.dirname(os.path.dirname(os.path.dirname(
             os.path.abspath(__file__)))), root_port)],
        env=env)
    _t.sleep(3.0)
    os.environ["DMLC_PS_ROOT_PORT"] = str(root_port)
    os.environ["DMLC_NUM_WORKER"] = "1"
    os.environ["DMLC_NUM_SERVER"] = "1"
    kv = DistKVStore("dist_sync")
    elems = int(size_mb * 1e6 / 4)
    val = nd.array(np.ones((elems,), np.float32))
    kv.init("bw", val)
    out = nd.zeros((elems,))
    kv.push("bw", val)
    kv.pull("bw", out=out)
    t0 = _t.perf_counter()
    for _ in range(runs):
        kv.push("bw", val)
        kv.pull("bw", out=out)
    dt = (_t.perf_counter() - t0) / runs
    moved = elems * 4 * 2  # push + pull payloads
    print("dist wire: payload=%.1fMB round-trip=%.1fms throughput=%.2f GB/s"
          % (elems * 4 / 1e6, dt * 1e3, moved / dt / 1e9))
    kv.stop()
    server.wait(timeout=30)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--size-mb", type=float, default=64.0)
    ap.add_argument("--runs", type=int, default=10)
    ap.add_argument("--dist", action="store_true",
                    help="measure the dist-kvstore TCP wire instead")
    args = ap.parse_args()

    if args.dist:
        measure_dist(args.size_mb, args.runs)
        return

    import jax

    try:
        # the image's sitecustomize imports jax before JAX_PLATFORMS is
        # read; the package's import-time guard pushes the override
        # through the config API under the canonical rule
        # (mxnet_tpu.__init__._platform_override_needed)
        import mxnet_tpu  # noqa: F401
    except Exception:
        pass
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    devs = jax.devices()
    n = len(devs)
    if n < 2:
        print("single device (%s): nothing to all-reduce; use the "
              "virtual CPU mesh (see --help)" % devs)
        return
    mesh = Mesh(np.array(devs), ("d",))
    elems = int(args.size_mb * 1e6 / 4)
    x = jnp.ones((n, elems), jnp.float32)
    x = jax.device_put(x, jax.sharding.NamedSharding(mesh, P("d", None)))

    @jax.jit
    def allreduce(v):
        return jax.shard_map(
            lambda s: jax.lax.psum(s, "d"),
            mesh=mesh, in_specs=P("d", None), out_specs=P("d", None),
        )(v)

    out = allreduce(x)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(args.runs):
        out = allreduce(out)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / args.runs
    # ring all-reduce moves 2*(n-1)/n of the payload per device
    payload = elems * 4
    algo_bw = payload * 2 * (n - 1) / n / dt / 1e9
    print("devices=%d payload=%.1fMB time=%.3fms alg_bandwidth=%.2f GB/s"
          % (n, payload / 1e6, dt * 1e3, algo_bw))


if __name__ == "__main__":
    main()
