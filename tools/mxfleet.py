#!/usr/bin/env python
"""mxfleet: run and operate a fleet of serve replicas from the CLI.

The fleet front (``mxnet_tpu/serve/fleet.py``, docs/serving.md "Fleet
serving") routes over N ``LlamaServer`` replicas with queue-depth-aware
power-of-two-choices routing, bounded retries + opt-in hedging,
circuit-breaker ejection, and zero-dropped-request rolling deploys.

Subcommands:

  serve   Start N in-process replicas from one bundle behind a
          FleetRouter HTTP front (the one-process twin of running
          ``python -m mxnet_tpu.serve`` N times behind a balancer)::

              python tools/mxfleet.py serve --bundle llama.mxaot \\
                  --replicas 3 --port 8000

          Or front replicas that are already running elsewhere::

              python tools/mxfleet.py serve --replica http://h1:8000 \\
                  --replica http://h2:8000 --port 9000

          SIGTERM/Ctrl-C drains every local replica, then exits.

  status  One probe sweep over the replicas, printed as a table::

              python tools/mxfleet.py status --replica http://h1:8000 \\
                  --replica http://h2:8000

          Columns: ok, draining, queue depth, TPOT p50, uptime,
          bundle_sha — a version-drift check across the fleet is one
          glance at the last column.

  top     Live fleet view off a running router front's ``/healthz``
          (docs/observability.md "Fleet observability")::

              python tools/mxfleet.py top --router http://localhost:9000

          Redraws every ``--interval`` seconds (``--once`` prints a
          single frame and exits — the scriptable form): one row per
          replica with breaker state (ok / EJECTED / draining /
          deploying), queue depth, in-flight count, TPOT EMA, arena
          utilization and consecutive failures, under a fleet header
          with completed/retried/hedged/dropped totals and any burning
          SLOs.
"""
from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)


def _replicas_from_args(args):
    from mxnet_tpu.serve.fleet import HttpReplica

    return [HttpReplica(url) for url in args.replica or ()]


def _cmd_serve(args):
    from mxnet_tpu.serve.fleet import FleetRouter
    from mxnet_tpu.serve.server import LlamaServer

    replicas = _replicas_from_args(args)
    servers = []
    if args.bundle:
        for _ in range(args.replicas):
            servers.append(LlamaServer(
                args.bundle, queue_depth=args.queue_depth).start())
        replicas.extend(servers)
    if not replicas:
        print("nothing to serve: pass --bundle (local replicas) and/or "
              "--replica URL", file=sys.stderr)
        return 2
    router = FleetRouter(replicas).start()
    host, port = router.serve_http(port=args.port, host=args.host)
    term = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: term.set())
    print("serving fleet n=%d on http://%s:%d (%d local, %d remote)"
          % (len(replicas), host, port, len(servers),
             len(replicas) - len(servers)))
    try:
        term.wait()
    except KeyboardInterrupt:
        pass
    stragglers = 0
    for srv in servers:
        stragglers += srv.drain(timeout=args.drain_timeout)
    router.stop()
    for srv in servers:
        srv.stop()
    if stragglers:
        print("drain timed out: %d request(s) failed typed" % stragglers)
    return 0


def _cmd_status(args):
    from mxnet_tpu.serve.fleet import HttpReplica

    rows = []
    for url in args.replica:
        r = HttpReplica(url)
        try:
            doc = r.probe()
        except Exception as e:  # noqa: BLE001 — a dead replica is a row
            rows.append((r.name, "DOWN", "-", "-", "-", "-",
                         "%s: %s" % (type(e).__name__, e)))
            continue
        rows.append((r.name,
                     "ok" if doc.get("ok") else "NOT-OK",
                     "yes" if doc.get("draining") else "no",
                     str(doc.get("queue_depth", "?")),
                     "%.4f" % doc.get("tpot_p50_s", 0.0),
                     "%.0fs" % doc.get("uptime_s", 0.0),
                     str(doc.get("bundle_sha"))))
    print("%-28s %-7s %-6s %-6s %-8s %-8s %s"
          % ("replica", "health", "drain", "queue", "tpot", "uptime",
             "bundle_sha"))
    for row in rows:
        print("%-28s %-7s %-6s %-6s %-8s %-8s %s" % row)
    shas = {row[6] for row in rows if row[1] == "ok"}
    if len(shas) > 1:
        print("WARNING: fleet has diverged across %d bundles: %s"
              % (len(shas), ", ".join(sorted(shas))))
        return 1
    return 0


def _replica_state(doc):
    if doc.get("ejected"):
        return "EJECTED"
    if doc.get("draining"):
        return "draining"
    if doc.get("deploying"):
        return "deploying"
    return "ok" if doc.get("ok") else "NOT-OK"


def _top_frame(body):
    slo = body.get("slo") or {}
    burning = slo.get("burning") or []
    lines = ["fleet: %d/%d healthy  completed=%s failed=%s retried=%s "
             "hedged=%s ejections=%s dropped=%s%s%s"
             % (body.get("replicas_healthy", 0),
                body.get("replicas_total", 0),
                body.get("completed", 0), body.get("failed", 0),
                body.get("retried", 0), body.get("hedged", 0),
                body.get("ejections", 0), body.get("dropped", 0),
                "  SHEDDING" if slo.get("shedding") else "",
                "  BURNING:" + ",".join(burning) if burning else "")]
    fmt = "%-28s %-10s %6s %9s %9s %7s %9s"
    lines.append(fmt % ("replica", "state", "queue", "inflight",
                        "tpot(s)", "arena", "failures"))
    for name in sorted(body.get("replicas", {})):
        doc = body["replicas"][name]
        lines.append(fmt % (
            name, _replica_state(doc),
            str(doc.get("queue_depth", "?")),
            str(doc.get("inflight", "?")),
            "%.4f" % float(doc.get("tpot_p50_s") or 0.0),
            "%3.0f%%" % (100.0 * float(doc.get("arena_utilization")
                                       or 0.0)),
            str(doc.get("failures", 0))))
    return "\n".join(lines)


def _cmd_top(args):
    import json
    import time
    import urllib.request

    url = args.router.rstrip("/") + "/healthz"
    while True:
        try:
            # the fleet front answers /healthz with 503 when nothing is
            # routable — that is still a frame worth rendering
            try:
                with urllib.request.urlopen(url, timeout=5) as resp:
                    body = json.loads(resp.read().decode())
            except urllib.error.HTTPError as e:
                body = json.loads(e.read().decode())
        except Exception as e:  # noqa: BLE001 — a dead router is a frame
            body = None
            frame = "router %s unreachable: %s: %s" \
                % (args.router, type(e).__name__, e)
        if body is not None:
            frame = _top_frame(body)
        if args.once:
            print(frame)
            return 0 if body is not None and body.get("ok") else 1
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        time.sleep(args.interval)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="mxfleet", description=__doc__,
                                 formatter_class=argparse.
                                 RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("serve", help="run a FleetRouter front")
    sp.add_argument("--bundle", default=None,
                    help="MXAOT1 bundle for in-process replicas")
    sp.add_argument("--replicas", type=int, default=3,
                    help="local replica count when --bundle is given")
    sp.add_argument("--replica", action="append", default=None,
                    metavar="URL", help="remote replica base URL "
                    "(repeatable)")
    sp.add_argument("--port", type=int, default=8000)
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--queue-depth", type=int, default=None)
    sp.add_argument("--drain-timeout", type=float, default=None)
    sp.set_defaults(fn=_cmd_serve)

    st = sub.add_parser("status", help="probe replicas, print a table")
    st.add_argument("--replica", action="append", required=True,
                    metavar="URL", help="replica base URL (repeatable)")
    st.set_defaults(fn=_cmd_status)

    tp = sub.add_parser("top", help="live fleet view off a router front")
    tp.add_argument("--router", required=True, metavar="URL",
                    help="FleetRouter HTTP front base URL")
    tp.add_argument("--interval", type=float, default=2.0,
                    help="seconds between redraws (default 2)")
    tp.add_argument("--once", action="store_true",
                    help="print one frame and exit (no screen clears)")
    tp.set_defaults(fn=_cmd_top)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
