#!/usr/bin/env python
"""Doc-drift check: every registered ``mxnet_*`` metric family must have
a row in docs/observability.md.

Three PRs in a row hand-synced the metric catalog table; this makes the
strict-lint CI job fail instead when someone registers a new family
(``telemetry.counter/gauge/histogram("mxnet_...")``) without documenting
it.

Mechanics: an AST walk over ``mxnet_tpu/`` collects every string-literal
family name passed to a counter/gauge/histogram call; the docs side
collects every ``mxnet_*`` code span in docs/observability.md, expanding
the table's ``_suffix`` shorthand (a cell like
`` `mxnet_engine_segment_cache_hits_total` / `_misses_total` `` also
documents ``mxnet_engine_segment_cache_misses_total`` — each shorthand
combines with every underscore-prefix of the last full name on the
line, so the check never needs to guess which split was meant).

Exit status 1 lists the undocumented families.  Run directly or via the
mxlint CI job; tests/test_docs.py keeps it honest in tier-1.
"""
from __future__ import annotations

import ast
import os
import re
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_REGISTRY_CALLS = {"counter", "gauge", "histogram"}
_CODE_SPAN = re.compile(r"`([A-Za-z0-9_]+)`")


def registered_families(root=None):
    """Every string-literal ``mxnet_*`` family passed to a registry call
    anywhere under ``root`` (default: the mxnet_tpu package)."""
    root = root or os.path.join(_REPO_ROOT, "mxnet_tpu")
    found = set()
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path) as f:
                try:
                    tree = ast.parse(f.read(), filename=path)
                except SyntaxError:
                    continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                func = node.func
                name = func.attr if isinstance(func, ast.Attribute) \
                    else (func.id if isinstance(func, ast.Name) else None)
                if name not in _REGISTRY_CALLS:
                    continue
                arg0 = node.args[0]
                if isinstance(arg0, ast.Constant) \
                        and isinstance(arg0.value, str) \
                        and arg0.value.startswith("mxnet_"):
                    found.add(arg0.value)
    return found


def documented_families(md_path=None):
    """Every ``mxnet_*`` code span in the doc, with ``_suffix`` shorthand
    expanded against the last full name on the same line."""
    md_path = md_path or os.path.join(_REPO_ROOT, "docs",
                                      "observability.md")
    with open(md_path) as f:
        text = f.read()
    out = set()
    for line in text.splitlines():
        base = None
        for span in _CODE_SPAN.findall(line):
            if span.startswith("mxnet_"):
                out.add(span)
                base = span
            elif span.startswith("_") and base:
                # `_misses_total` after `..._hits_total`: try every
                # underscore split of the base — over-approximating is
                # harmless, the check only tests membership
                for i, ch in enumerate(base):
                    if ch == "_":
                        out.add(base[:i] + span)
    return out


def missing_families(root=None, md_path=None):
    return sorted(registered_families(root) - documented_families(md_path))


def main(argv=None):
    missing = missing_families()
    if missing:
        print("ERROR: %d registered metric families have no row in "
              "docs/observability.md:" % len(missing), file=sys.stderr)
        for name in missing:
            print("  - %s" % name, file=sys.stderr)
        print("add a row to the metric catalog table (or fix the name).",
              file=sys.stderr)
        return 1
    print("metric docs in sync: %d families documented"
          % len(registered_families()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
