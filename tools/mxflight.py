#!/usr/bin/env python
"""mxflight: read mxnet_tpu flight-recorder dumps from the command line.

A flight dump is the black box a process leaves behind when it dies (or
when ``mx.telemetry.flight.dump()`` is called): the last N engine
push/flush/sync events, kvstore RPCs, fault injections, serve
scheduler transitions and elastic-membership changes, with monotonic
sequence numbers and a wall-clock anchor.  Arm crash dumps with
``MXNET_FLIGHT_DUMP=flight-{rank}.json``.

Post-mortem of an elastic job: ``show dump.json --kind membership``
filters to the eviction/join/epoch timeline — each ``membership.evict``
names the lost rank's last RPC (``last_rpc``/``last_seq``), which is
usually the first question after a scale-down.

Resource-leak triage: ``show dump.json --kind res`` keeps the
``res.leak`` / ``res.double_free`` events the ``MXNET_RESCHECK=1``
sanitizer records — each names the handle kind, owner, scope and the
acquisition site, so a leak found by chaos CI is attributable without
re-running the job.

Subcommands:

  show    Pretty-print one or more dumps, newest last::

              python tools/mxflight.py show flight-0.json --kind kv --last 20

          ``--kind`` filters by exact event kind or dotted prefix
          (``engine`` matches ``engine.push``/``engine.flush``/...),
          ``--trace ID`` slices to one request's events (the ``tid``
          every serve/fleet event carries — a fleet trace id follows
          one request across router retries, hedges and the winning
          replica), ``--last N`` keeps the N most recent events per
          dump.

  merge   Merge multi-rank dumps into ONE chrome://tracing file on a
          correlated timeline (each dump's wall anchor aligns it, the
          same mechanism as ``tools/mxtrace.py merge``)::

              python tools/mxflight.py merge flight-0.json flight-1.json \\
                  -o merged.json --labels rank0 rank1

          Pass profiler traces too (``--with-trace worker0.json``) to
          overlay flight events onto the PR 5 span timeline — flight
          events render as instants above the profiler spans.
"""
from __future__ import annotations

import argparse
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)


def _fmt_event(e):
    extras = " ".join("%s=%s" % (k, v) for k, v in sorted(e.items())
                      if k not in ("seq", "ts", "kind"))
    return "%8d  %12.6f  %-20s %s" % (e.get("seq", -1), e.get("ts", 0.0),
                                      e.get("kind", "?"), extras)


def _cmd_show(args):
    from mxnet_tpu.telemetry import flight

    for path in args.dumps:
        doc = flight.load(path)
        meta = doc.get("meta", {})
        evs = doc.get("events", [])
        if args.kind:
            evs = [e for e in evs
                   if e.get("kind") == args.kind
                   or str(e.get("kind", "")).startswith(args.kind + ".")]
        if args.trace:
            evs = [e for e in evs if str(e.get("tid", "")) == args.trace]
        if args.last is not None:
            evs = evs[-args.last:]
        print("== %s  (pid %s, rank %s, reason %r, %d/%d events, "
              "%d dropped)" % (path, meta.get("pid"), meta.get("rank"),
                               meta.get("reason"), len(evs),
                               meta.get("recorded", len(evs)),
                               meta.get("dropped", 0)))
        print("%8s  %12s  %-20s %s" % ("seq", "ts(s)", "kind", "fields"))
        for e in evs:
            print(_fmt_event(e))
    return 0


def _cmd_merge(args):
    from mxnet_tpu.telemetry import flight, merge_traces

    inputs, labels = [], []
    for path in args.dumps:
        doc = flight.load(path)
        meta = doc.get("meta", {})
        inputs.append(flight.to_trace(doc))
        labels.append("flight:rank%s" % meta.get("rank", "?"))
    for path in args.with_trace or ():
        inputs.append(path)
        labels.append(os.path.basename(path))
    if args.labels:
        labels[:len(args.labels)] = args.labels
    merged = merge_traces(inputs, out=args.output, labels=labels)
    n = sum(1 for e in merged["traceEvents"] if e.get("ph") != "M")
    print("merged %d events from %d input(s) -> %s"
          % (n, len(inputs), args.output))
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="mxflight", description=__doc__,
                                 formatter_class=argparse.
                                 RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("show", help="pretty-print flight dumps")
    sp.add_argument("dumps", nargs="+", help="flight-recorder JSON dumps")
    sp.add_argument("--kind", default=None,
                    help="filter: exact kind or dotted prefix (kv, "
                         "engine, res)")
    sp.add_argument("--trace", default=None, metavar="ID",
                    help="keep only events stamped with this trace id "
                         "(serve/fleet 'tid' field)")
    sp.add_argument("--last", type=int, default=None,
                    help="keep only the N most recent events per dump")
    sp.set_defaults(fn=_cmd_show)

    mp = sub.add_parser("merge", help="merge dumps onto one timeline")
    mp.add_argument("dumps", nargs="+", help="flight-recorder JSON dumps")
    mp.add_argument("-o", "--output", default="merged_flight.json")
    mp.add_argument("--labels", nargs="*", default=None,
                    help="display name per input (default flight:rankN)")
    mp.add_argument("--with-trace", nargs="*", default=None,
                    help="profiler chrome-trace files to overlay")
    mp.set_defaults(fn=_cmd_merge)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
