"""Pure-JAX NHWC ResNet-50 train-step ceiling probe.

Hand-written minimal ResNet-50 v1 (bf16 activations, f32 BN stats, SGD
momentum) with no framework plumbing — measures what XLA:TPU delivers on
this chip for the same math, to separate framework overhead from compiler
ceiling.  Usage: python tools/rn50_ceiling.py [batch] [variant...]
variants:
  bf16stats — BN batch stats computed in bf16 instead of f32.
  s2d       — space-to-depth stem (the MLPerf TPU ResNet transform): the
              7x7/s2 conv over 3 input channels packs terribly onto the
              128x128 MXU (contraction dim 7*7*3=147 but channel dim 3);
              pad the kernel to 8x8 and fold a 2x2 space-to-depth block
              into channels, giving an equivalent 4x4/s1 conv over 12
              channels on a 112x112 grid.  Same math (zero-padded taps),
              MXU-friendly shape.
"""
import functools
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax

cache_dir = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

BF16_STATS = "bf16stats" in sys.argv
S2D = "s2d" in sys.argv
ONEPASS_STATS = "onepass" in sys.argv


def conv(x, w, stride=1):
    return lax.conv_general_dilated(
        x, w, (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def stem_s2d(x, w7):
    """7x7/s2 SAME stem conv, rewritten space-to-depth.

    Equivalence: SAME for k=7,s=2,in=224 pads (2,3); padding the kernel
    with one zero row/col (8x8) and the input to (2,4) keeps every tap
    aligned.  An 8x8/s2 conv is then exactly a 4x4/s1 conv on the 2x2
    space-to-depth transform of the input (block offset (di,dj) becomes
    a channel), with the kernel regrouped the same way.
    """
    w8 = jnp.pad(w7, ((0, 1), (0, 1), (0, 0), (0, 0)))
    xp = jnp.pad(x, ((0, 0), (2, 4), (2, 4), (0, 0)))
    n, h, w_, c = xp.shape
    xs = xp.reshape(n, h // 2, 2, w_ // 2, 2, c).transpose(
        0, 1, 3, 2, 4, 5).reshape(n, h // 2, w_ // 2, 4 * c)
    w4 = w8.reshape(4, 2, 4, 2, c, w7.shape[-1]).transpose(
        0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * c, w7.shape[-1])
    return lax.conv_general_dilated(
        xs, w4, (1, 1), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def bn_train(x, gamma, beta):
    if ONEPASS_STATS:
        # sibling sum/sumsq reduces over one input: XLA multi-output
        # fusion computes both in a single HBM pass (vs mean->var's two
        # dependent passes).  Probe uses shift c=0; the framework BN
        # shifts by the running mean to kill cancellation.
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=(0, 1, 2))
        msq = jnp.mean(x32 * x32, axis=(0, 1, 2))
        var = msq - mean * mean
        inv = (lax.rsqrt(var + 1e-5) * gamma.astype(jnp.float32))
        scale = inv.astype(x.dtype)
        shift = (beta.astype(jnp.float32) - mean * inv).astype(x.dtype)
        return x * scale + shift
    if BF16_STATS:
        mean = jnp.mean(x, axis=(0, 1, 2))
        var = jnp.var(x, axis=(0, 1, 2))
        inv = lax.rsqrt(var + jnp.bfloat16(1e-5)) * gamma
        return x * inv + (beta - mean * inv)
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=(0, 1, 2))
    var = jnp.var(x32, axis=(0, 1, 2))
    inv = (lax.rsqrt(var + 1e-5) * gamma.astype(jnp.float32))
    scale = inv.astype(x.dtype)
    shift = (beta.astype(jnp.float32) - mean * inv).astype(x.dtype)
    return x * scale + shift


def bottleneck(x, p, stride, project):
    out = bn_train(conv(x, p["w1"], stride), p["g1"], p["b1"])
    out = jax.nn.relu(out)
    out = bn_train(conv(out, p["w2"]), p["g2"], p["b2"])
    out = jax.nn.relu(out)
    out = bn_train(conv(out, p["w3"]), p["g3"], p["b3"])
    if project:
        sc = bn_train(conv(x, p["ws"], stride), p["gs"], p["bs"])
    else:
        sc = x
    return jax.nn.relu(out + sc)


LAYERS = [(3, 256, 1), (4, 512, 2), (6, 1024, 2), (3, 2048, 2)]


def init_params(key):
    rs = np.random.RandomState(0)
    P = {}

    def W(*shape):
        fan_in = int(np.prod(shape[:-1]))
        return jnp.asarray(
            rs.randn(*shape) * np.sqrt(2.0 / fan_in), jnp.bfloat16)

    P["stem_w"] = W(7, 7, 3, 64)
    P["stem_g"] = jnp.ones((64,), jnp.bfloat16)
    P["stem_b"] = jnp.zeros((64,), jnp.bfloat16)
    in_ch = 64
    for si, (n, ch, stride) in enumerate(LAYERS):
        mid = ch // 4
        for bi in range(n):
            p = {}
            cin = in_ch if bi == 0 else ch
            s = stride if bi == 0 else 1
            p["w1"] = W(1, 1, cin, mid)
            p["w2"] = W(3, 3, mid, mid)
            p["w3"] = W(1, 1, mid, ch)
            for t in ("1", "2", "3"):
                p["g" + t] = jnp.ones(
                    (mid if t != "3" else ch,), jnp.bfloat16)
                p["b" + t] = jnp.zeros(
                    (mid if t != "3" else ch,), jnp.bfloat16)
            if bi == 0:
                p["ws"] = W(1, 1, cin, ch)
                p["gs"] = jnp.ones((ch,), jnp.bfloat16)
                p["bs"] = jnp.zeros((ch,), jnp.bfloat16)
            P["s%d_%d" % (si, bi)] = p
        in_ch = ch
    P["fc_w"] = W(2048, 1000)
    P["fc_b"] = jnp.zeros((1000,), jnp.bfloat16)
    return P


def forward(P, x):
    x = stem_s2d(x, P["stem_w"]) if S2D else conv(x, P["stem_w"], 2)
    x = jax.nn.relu(bn_train(x, P["stem_g"], P["stem_b"]))
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1),
                          (1, 2, 2, 1), "SAME")
    for si, (n, ch, stride) in enumerate(LAYERS):
        for bi in range(n):
            x = bottleneck(x, P["s%d_%d" % (si, bi)],
                           stride if bi == 0 else 1, bi == 0)
    x = jnp.mean(x, axis=(1, 2))
    return x.astype(jnp.float32) @ P["fc_w"].astype(jnp.float32) \
        + P["fc_b"].astype(jnp.float32)


def loss_fn(P, x, y):
    logits = forward(P, x)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1))


@jax.jit
def train_n(P, M, x, y, n):
    def step(i, carry):
        P, M, _ = carry
        loss, g = jax.value_and_grad(loss_fn)(P, x, y)
        newM = jax.tree_util.tree_map(
            lambda m, gg: 0.9 * m + gg.astype(m.dtype), M, g)
        newP = jax.tree_util.tree_map(
            lambda p, m: (p.astype(jnp.float32)
                          - 0.1 * m.astype(jnp.float32)).astype(p.dtype),
            P, newM)
        return newP, newM, loss

    return lax.fori_loop(0, n, step, (P, M, jnp.float32(0)))


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 and sys.argv[1].isdigit() \
        else 256
    P = init_params(0)
    M = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32), P)
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.rand(batch, 224, 224, 3), jnp.bfloat16)
    y = jnp.asarray(rs.randint(0, 1000, batch), jnp.int32)
    n = 10
    # RN50_COMPILER_OPTS: JSON dict of XLA compiler options, passed per
    # PJRT compile (reaches the TPU compiler even when XLA_FLAGS only
    # hits the local CPU XLA — e.g. under a remote-compile tunnel)
    run = train_n
    opts = os.environ.get("RN50_COMPILER_OPTS")
    if opts:
        import json

        run = train_n.lower(P, M, x, y, n).compile(
            compiler_options=json.loads(opts))
        print("compiler options: %s" % opts, file=sys.stderr)
    t0 = time.perf_counter()
    out = run(P, M, x, y, n)
    jax.block_until_ready(out)
    print("compile+first: %.1fs loss=%.3f"
          % (time.perf_counter() - t0, float(out[2])), file=sys.stderr)
    t0 = time.perf_counter()
    out = run(P, M, x, y, n)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    print("pure-jax rn50 b%d%s: %.3fs -> %.1f img/s"
          % (batch, " bf16stats" if BF16_STATS else "", dt, batch * n / dt))


if __name__ == "__main__":
    main()
