"""Headline benchmark: ResNet-50 training throughput, one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: the reference's best published single-GPU ResNet-50 training
number — 363.69 img/s (batch 128, 1x V100, fp32; BASELINE.md, perf.md:254).

The whole train step (fwd+bwd+SGD) is one XLA executable with donated
buffers (mxnet_tpu.parallel.JitTrainStep); inputs are bf16 NHWC-friendly
batches fed asynchronously.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

BASELINE_IMG_S = 363.69


def main():
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import vision

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    platform = jax.devices()[0].platform

    mx.random.seed(0)
    net = vision.resnet50_v1()
    net.initialize(mx.init.Xavier())
    if platform != "cpu":
        net.cast('bfloat16')  # MXU-native dtype; BN math still f32 inside

    step = parallel.JitTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        'sgd', {'learning_rate': 0.1, 'momentum': 0.9})

    rng = np.random.RandomState(0)
    dtype = np.float32 if platform == "cpu" else 'bfloat16'
    x = rng.rand(batch, 3, 224, 224).astype(np.float32)
    if dtype != np.float32:
        import jax.numpy as jnp
        x = jnp.asarray(x, jnp.bfloat16)
    y = rng.randint(0, 1000, batch).astype(np.float32)

    # warmup: first call compiles
    for _ in range(3):
        loss = step.step(x, y)
    jax.block_until_ready(loss)

    n_steps = 20
    t0 = time.perf_counter()
    for _ in range(n_steps):
        loss = step.step(x, y)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    img_s = batch * n_steps / dt
    print(json.dumps({
        "metric": "resnet50_train_throughput",
        "value": round(img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
    }))


if __name__ == "__main__":
    main()
