"""Headline benchmark: ResNet-50 training throughput, one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"platform", "fallback", "metrics"} — the headline ResNet-50 train
number at top level, plus a "metrics" array carrying the secondary
benchmarks (inference, BERT, Llama, dispatch, cold start) so one driver
artifact records the whole headline set.  "platform" is the PJRT platform the numbers were
measured on and "fallback" is True iff the accelerator was unreachable
and the run degraded to CPU — a fallback number can never masquerade as
a chip number again.
Baseline: the reference's best published single-GPU ResNet-50 training
number — 363.69 img/s (batch 128, 1x V100, fp32; BASELINE.md, perf.md:254).

The whole train step (fwd+bwd+SGD) is one XLA executable with donated
buffers (mxnet_tpu.parallel.JitTrainStep); weights/activations in bf16
(MXU-native; accumulation stays f32 in hardware).

Robustness: backend init is retried (the tunnel to the chip can be
transiently unavailable), falls back to CPU with a reduced config so a
number is always printed, and every failure path emits diagnostics on
stderr before the JSON line.
"""
from __future__ import annotations

import json
import os
import sys
import time
import traceback

import numpy as np

BASELINE_IMG_S = 363.69


def _log(msg):
    print("[bench] %s" % msg, file=sys.stderr, flush=True)


def _init_backend():
    """Initialize jax's backend with retries.

    Returns ``(platform, fallback)`` — ``fallback`` is True iff the
    ambient/requested backend could not be brought up and the benchmark
    dropped to CPU.  The flag travels into the emitted JSON so a driver
    or dashboard can never mistake an outage-degraded number for a real
    chip regression (round-3 lesson: BENCH_r03 recorded a CPU 1.07
    img/s with nothing machine-readable marking it as a fallback).
    """
    import jax

    # persistent executable cache: the ResNet-50 train step takes XLA
    # minutes to compile; cached (workspace-local, gitignored), re-runs
    # of this benchmark on the same machine skip most of the compile.
    try:
        cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 ".jax_cache")
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:
        _log("compilation cache unavailable: %s" % e)
    # honor an explicit JAX_PLATFORMS override in this process too: the
    # package's import-time guard applies the canonical rule (redirect
    # unless the env list is a prefix of the config list — see
    # mxnet_tpu.__init__._platform_override_needed; the round-4 OOM came
    # from stripping a plugin's "<accel>,cpu" staging platform to bare
    # "<accel>").  Importing the package does not initialize a backend.
    try:
        import mxnet_tpu  # noqa: F401 — import runs _honor_platform_env
    except Exception:
        pass
    last = None
    # the tunnel to the chip can be down for extended periods; probe in a
    # SUBPROCESS with a hard timeout (jax.devices() can hang rather than
    # raise), retrying across a worst-case ~10-minute window (6 probes
    # of <=60s + backoff sleeps) before CPU fallback
    import subprocess

    n_attempts = 6
    for attempt in range(n_attempts):
        try:
            # the probe honors a JAX_PLATFORMS env override through the
            # config API (the image may have pinned another platform via
            # config at interpreter startup, and config beats env)
            probe = subprocess.run(
                [sys.executable, "-c",
                 # mirrors _platform_override_needed (kept jax-only so
                 # the probe stays fast under a dead tunnel)
                 "import os, jax\n"
                 "p = os.environ.get('JAX_PLATFORMS') or ''\n"
                 "c = str(getattr(jax.config, 'jax_platforms', '') or '')\n"
                 "pl = [s.strip() for s in p.split(',') if s.strip()]\n"
                 "cl = [s.strip() for s in c.split(',') if s.strip()]\n"
                 "if pl and pl != cl[:len(pl)]:\n"
                 "    jax.config.update('jax_platforms', p)\n"
                 "print(jax.devices()[0].platform)"],
                capture_output=True, text=True, timeout=60)
            if probe.returncode == 0 and probe.stdout.strip():
                # the probe just initialized the backend successfully in
                # a fresh process; the parent's own init could still
                # stall if the tunnel drops in between, so keep a
                # watchdog that aborts to CPU rather than hanging the
                # "a number is always printed" guarantee
                import threading

                done = threading.Event()
                result = {}

                def _init():
                    try:
                        result["devs"] = jax.devices()
                    except Exception as e:  # noqa: BLE001
                        result["err"] = e
                    done.set()

                threading.Thread(target=_init, daemon=True).start()
                if done.wait(timeout=120) and "devs" in result:
                    devs = result["devs"]
                    _log("devices: %s" % (devs,))
                    return devs[0].platform, False
                last = result.get("err", "parent backend init stalled")
            else:
                last = (probe.stderr.strip() or probe.stdout.strip()
                        or "probe exited %d" % probe.returncode)[-200:]
        except Exception as e:  # includes probe TimeoutExpired
            last = e
        _log("backend init attempt %d failed: %s" % (attempt + 1, last))
        if attempt < n_attempts - 1:
            time.sleep(10 * (attempt + 1))
    _log("all backend attempts failed (%s); falling back to CPU" % (last,))
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    return jax.devices()[0].platform, True


def _median_windows(run_window, n_windows=5, label=""):
    """Median rate over >=3 separately-timed windows.

    The tunnel adds multi-ms jitter per dispatch round trip; a single
    window under-measures by up to ~20% (round-4 verdict: doc numbers
    exceeded the driver artifact by 5-19%).  Each window is long enough
    to amortize dispatch, and the MEDIAN of 5 windows is the number of
    record — reproducible within ~3% across driver runs.
    """
    rates = []
    for _ in range(n_windows):
        rates.append(run_window())
    med = sorted(rates)[len(rates) // 2]
    _log("%s windows: [%s] -> median %.1f"
         % (label, ", ".join("%.1f" % r for r in rates), med))
    return med


def _run_bert(platform):
    """Secondary benchmark (`python bench.py bert`): BERT-base MLM train
    throughput, whole step as one executable.  No reference number exists
    in-tree (the reference era predates BERT), so vs_baseline is 0."""
    import jax
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import bert

    on_accel = platform not in ("cpu",)
    batch = 32 if on_accel else 2
    seqlen = 128 if on_accel else 16
    n_steps = 10 if on_accel else 2
    mx.random.seed(0)
    net = bert.bert_base(vocab_size=30522) if on_accel else \
        bert.bert_small(vocab_size=1000)
    net.initialize(mx.init.Xavier())
    if on_accel:
        from mxnet_tpu import amp

        amp.init("bfloat16")
        amp.convert_hybrid_block(net)
    vocab = 30522 if on_accel else 1000

    class MLM(gluon.HybridBlock):
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def hybrid_forward(self, F, toks):
            _, _, logits = self.inner(toks)
            return F.reshape(logits, shape=(-1, vocab))

    step = parallel.JitTrainStep(
        MLM(net), gluon.loss.SoftmaxCrossEntropyLoss(),
        "adam", {"learning_rate": 1e-4})
    rng = np.random.RandomState(0)
    toks = rng.randint(0, vocab, (batch, seqlen)).astype(np.int32)
    labels = rng.randint(0, vocab, batch * seqlen).astype(np.float32)
    t0 = time.perf_counter()
    loss = step.step(toks, labels)
    jax.block_until_ready(loss)
    _log("bert compile+first step: %.1fs loss=%.3f"
         % (time.perf_counter() - t0, float(loss)))
    for _ in range(5):  # warm: async dispatch pipeline reaches steady state
        loss = step.step(toks, labels)
    jax.block_until_ready(loss)

    def window():
        t0 = time.perf_counter()
        for _ in range(n_steps * 2):
            l = step.step(toks, labels)
        jax.block_until_ready(l)
        return batch * n_steps * 2 / (time.perf_counter() - t0)

    sps = _median_windows(window, label="bert")
    _log("bert-base b%d seq%d: %.1f samples/s (%.0f tok/s)"
         % (batch, seqlen, sps, sps * seqlen))
    return sps


BASELINE_INFER_FP16 = 2085.51  # ResNet-50 inference b32 fp16, 1xV100 (perf.md:208)


def _run_infer(platform):
    """`python bench.py infer`: ResNet-50 inference throughput."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import amp, autograd
    from mxnet_tpu.gluon.model_zoo import vision

    on_accel = platform not in ("cpu",)
    batch = 32 if on_accel else 8  # b32: matches the reference's row
    image = 224 if on_accel else 64
    # 100 serial forwards per dispatch: at ~6k img/s a 20-step loop is
    # only ~100ms of device time, so tunnel round-trip jitter dominated
    # the measurement (observed 3.4k-6.1k img/s across runs); ~500ms
    # of device work amortizes it
    n_steps = 100 if on_accel else 2
    mx.random.seed(0)
    net = vision.resnet50_v1()
    net.initialize(mx.init.Xavier())
    if on_accel:
        amp.init("bfloat16")
        amp.convert_hybrid_block(net)
    net.hybridize()
    from jax import lax
    from mxnet_tpu.gluon import block as block_mod
    from mxnet_tpu.ndarray.ndarray import NDArray
    from mxnet_tpu import random as _random

    params = list(net.collect_params().values())
    net(mx.nd.array(np.random.RandomState(0).rand(
        1, 3, image, image).astype(np.float32)))  # resolve shapes
    dev = jax.devices()[0]
    ws = tuple(jax.device_put(jnp.asarray(p.data().data()), dev)
               for p in params)
    dtype = jnp.bfloat16 if on_accel else jnp.float32
    x = jax.device_put(
        jnp.asarray(np.random.RandomState(0).rand(
            batch, 3, image, image), dtype), dev)

    def fwd(xi, w_tuple):
        st = block_mod._trace_st()
        prev = (st.param_map, st.aux_updates, st.active)
        st.param_map = {id(p): NDArray(a)
                        for p, a in zip(params, w_tuple)}
        st.aux_updates = []
        st.active = True
        try:
            with autograd.predict_mode(), \
                    _random.trace_key_scope(jax.random.PRNGKey(0)):
                return net._forward_imperative(NDArray(xi)).data()
        finally:
            st.param_map, st.aux_updates, st.active = prev

    # n_steps serial forwards ON DEVICE in one dispatch: distinct input
    # per iteration, outputs consumed by an accumulator — immune to
    # host/tunnel pipelining artifacts
    @jax.jit
    def run_n(xb, w_tuple):
        def body(i, acc):
            out = fwd(xb + i.astype(dtype) * dtype(1e-3), w_tuple)
            return acc + out.astype(jnp.float32).sum()
        return lax.fori_loop(0, n_steps, body, jnp.float32(0.0))

    t0 = time.perf_counter()
    r = run_n(x, ws)
    jax.block_until_ready(r)
    _log("infer compile+first: %.1fs" % (time.perf_counter() - t0))

    def window():
        t0 = time.perf_counter()
        rr = run_n(x, ws)
        jax.block_until_ready(rr)
        return batch * n_steps / (time.perf_counter() - t0)

    img_s = _median_windows(window, label="infer")
    _log("resnet50 inference b%d: %.1f img/s" % (batch, img_s))
    return img_s


def _run_llama(platform):
    """`python bench.py llama [seqlen]`: decoder-LM (Llama-architecture)
    training throughput in tokens/s — RoPE + GQA + SwiGLU + Pallas flash
    attention FORWARD AND BACKWARD (no (T,T) buffer either direction, so
    long sequences fit: `bench.py llama 4096` trains seq-4096 without
    the old attention-recompute memory spike).  No reference number
    exists (the reference era predates decoder LMs), so vs_baseline is 0."""
    import jax
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import llama

    on_accel = platform not in ("cpu",)
    argv_seq = [a for a in sys.argv[1:] if a.isdigit()]
    batch = 8 if on_accel else 2
    seqlen = int(argv_seq[0]) if argv_seq else (512 if on_accel else 16)
    if on_accel and seqlen >= 2048:
        batch = max(1, 8 * 512 // seqlen)  # keep tokens/step comparable
    n_steps = 10 if on_accel else 2
    vocab = 32000 if on_accel else 512
    mx.random.seed(0)
    if on_accel:
        # ~160M-param GPT-2-medium-class geometry with GQA
        net = llama.LlamaModel(vocab, units=768, hidden_size=2048,
                               num_layers=12, num_heads=12, num_kv_heads=4)
    else:
        net = llama.llama_small()
    net.initialize(mx.init.Xavier())
    if on_accel:
        from mxnet_tpu import amp

        amp.init("bfloat16")
        amp.convert_hybrid_block(net)

    class LM(gluon.HybridBlock):
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def hybrid_forward(self, F, toks):
            return F.reshape(self.inner(toks), shape=(-1, vocab))

    step = parallel.JitTrainStep(
        LM(net), gluon.loss.SoftmaxCrossEntropyLoss(),
        "adamw", {"learning_rate": 1e-4})
    rng = np.random.RandomState(0)
    toks = rng.randint(0, vocab, (batch, seqlen)).astype(np.int32)
    labels = rng.randint(0, vocab, batch * seqlen).astype(np.float32)
    t0 = time.perf_counter()
    loss = step.step(toks, labels)
    jax.block_until_ready(loss)
    _log("llama compile+first step: %.1fs loss=%.3f"
         % (time.perf_counter() - t0, float(loss)))
    for _ in range(5):
        loss = step.step(toks, labels)
    jax.block_until_ready(loss)

    def window():
        t0 = time.perf_counter()
        for _ in range(n_steps * 2):
            l = step.step(toks, labels)
        jax.block_until_ready(l)
        return batch * seqlen * n_steps * 2 / (time.perf_counter() - t0)

    tok_s = _median_windows(window, label="llama")
    _log("llama b%d seq%d: %.0f tokens/s" % (batch, seqlen, tok_s))
    return tok_s


def _run(platform):
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import vision

    on_accel = platform not in ("cpu",)
    argv_batch = [a for a in sys.argv[1:] if a.isdigit()]
    # batch 384 measured fastest on a 16G v5e (2360 img/s vs 2336 @256,
    # 2337 @512 — bigger batches hit memory pressure, smaller ones
    # underfill the MXU); override with `python bench.py <batch>`
    batch = int(argv_batch[0]) if argv_batch else (384 if on_accel else 8)
    image = 224 if on_accel else 64
    n_steps = 10 if on_accel else 2

    mx.random.seed(0)
    net = vision.resnet50_v1()
    net.initialize(mx.init.Xavier())
    if on_accel:
        # AMP: matmul/conv in bf16 (MXU-native), sensitive ops in f32
        from mxnet_tpu import amp
        amp.init('bfloat16')
        amp.convert_hybrid_block(net)

    step = parallel.JitTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        'sgd', {'learning_rate': 0.1, 'momentum': 0.9})

    rng = np.random.RandomState(0)
    x = rng.rand(batch, 3, image, image).astype(np.float32)
    if on_accel:
        x = jnp.asarray(x, jnp.bfloat16)
    y = rng.randint(0, 1000, batch).astype(np.float32)

    _log("compiling train step (platform=%s batch=%d image=%d)..."
         % (platform, batch, image))
    t0 = time.perf_counter()
    loss = step.step(x, y)
    jax.block_until_ready(loss)
    _log("compile+first step: %.1fs, loss=%.4f"
         % (time.perf_counter() - t0, float(loss)))
    t1 = time.perf_counter()
    loss = step.step(x, y)  # warm step (may recompile once: the donated
    jax.block_until_ready(loss)  # weights come back with device layouts)
    # NOTE: the per-step path is slower than the fused loop below — each
    # step() pays one host->device dispatch over the tunnel, which the
    # n-step device-side loop amortizes; the loop is the honest number
    _log("warm step: %.1fs (per-step dispatch; loop below amortizes it)"
         % (time.perf_counter() - t1))

    # measured loop runs ON DEVICE (one dispatch for n_steps fused
    # fwd+bwd+opt iterations) so host/tunnel latency doesn't pollute the
    # throughput number
    t1 = time.perf_counter()
    loss = step.step_n(n_steps, x, y)
    jax.block_until_ready(loss)
    _log("step_n compile+run: %.1fs" % (time.perf_counter() - t1))

    def window():
        t0 = time.perf_counter()
        l = step.step_n(n_steps, x, y)
        jax.block_until_ready(l)
        return batch * n_steps / (time.perf_counter() - t0)

    img_s = _median_windows(window, label="train")
    _log("measured %d-step windows -> %.2f img/s" % (n_steps, img_s))
    return img_s


def _dispatch_rate(bulk_size, chain_len=20, record=False, label=None):
    """Imperative ops/sec through a ``chain_len``-op elementwise chain.

    The op-bulking microbenchmark (docs/perf.md): the same python loop is
    timed under the engine DEFAULT (``bulk_size=None`` — BulkEngine
    defers the whole chain into one segment since PR 6), with bulking
    forced off (``bulk_size=0`` — one jitted dispatch per op, the
    pre-BulkEngine hot path), or with an explicit scope cap.  Host
    dispatch dominates, so the number is CPU-stable and platform jitter
    barely moves it.

    ``record=True`` runs the chain under ``autograd.record()`` and calls
    ``backward()`` each iteration — the training-shaped variant that
    segment-spanning autograd unlocked (the recorded chain still flushes
    as ONE segment; only forward chain ops are counted, so the rate is
    directly comparable to the unrecorded variants and backward rides as
    overhead).
    """
    from contextlib import nullcontext

    from mxnet_tpu import autograd as _autograd
    from mxnet_tpu import engine as _engine
    from mxnet_tpu import nd

    n_iters = max(6, 600 // chain_len)
    x = nd.ones((64, 64))
    if record:
        x.attach_grad()

    def run_iter():
        scope = nullcontext() if bulk_size is None else _engine.bulk(bulk_size)
        with scope:
            if record:
                with _autograd.record():
                    a = x
                    for i in range(chain_len):
                        a = (a + 1.0) if i % 2 else (a * 1.0009765625)
                    loss = a.sum()
                loss.backward()
                x.grad.wait_to_read()
            else:
                a = x
                for i in range(chain_len):
                    a = (a + 1.0) if i % 2 else (a * 1.0009765625)
                a.wait_to_read()

    for _ in range(3):  # warmup: compile both the per-op and segment paths
        run_iter()

    def window():
        t0 = time.perf_counter()
        for _ in range(n_iters):
            run_iter()
        return chain_len * n_iters / (time.perf_counter() - t0)

    if label is None:
        label = "dispatch_%s" % ("default" if bulk_size is None
                                 else "bulked" if bulk_size else "eager")
    return _median_windows(window, label=label)


def _run_dispatch_eager(platform):
    # ISSUE 6: the "eager" workload now runs under the engine DEFAULT —
    # with BulkEngine the default engine, the unmodified user loop is the
    # thing being scored (MXNET_ENGINE_TYPE=NaiveEngine restores true
    # per-op dispatch; the metric name is kept for artifact continuity)
    return _dispatch_rate(None)


def _run_dispatch_eager_notelemetry(platform):
    """Eager dispatch with metrics collection OFF — paired with
    ``imperative_dispatch_eager`` (telemetry on by default) this turns
    the "near-zero telemetry overhead" claim into a tracked number
    (acceptance: on/off gap <= 3%; docs/observability.md)."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.telemetry import flight

    was_on = telemetry.enabled()
    flight_on = flight.enabled()
    telemetry.disable()
    flight.disable()
    try:
        return _dispatch_rate(None, label="dispatch_default_notelemetry")
    finally:
        if was_on:
            telemetry.enable()
        if flight_on:
            flight.enable()


def _run_dispatch_bulked(platform):
    return _dispatch_rate(20)


def _run_dispatch_bulked_train(platform):
    """20-op chain under ``autograd.record()`` + ``backward()`` — the
    training-shaped dispatch number segment-spanning autograd unlocked
    (before ISSUE 6 the record boundary flushed per op)."""
    return _dispatch_rate(None, record=True, label="dispatch_bulked_train")


def _run_dispatch_bulked_long(platform):
    """64-op chain — exercises the raised MXNET_EXEC_BULK_EXEC_MAX_NODE
    cap (one segment in the le64 cache tier per iteration)."""
    return _dispatch_rate(None, chain_len=64, label="dispatch_bulked_long")


def _cold_probe(workload):
    """Subprocess entry for the cold-start benchmark (`--cold-probe <w>`).

    Times compile+first-step for a small training workload in THIS fresh
    process and prints a parseable ``COLD_START_SECONDS=`` line on
    stdout.  The parent (``_run_cold_start``) owns the compilation-cache
    contract through the ``MXNET_COMPILE_CACHE*`` env vars, which
    ``import mxnet_tpu`` applies (compile_cache.configure) — so this
    path must NOT go through ``_init_backend``, whose workspace-local
    ``.jax_cache`` override would shadow the parent's cache dir and make
    every "cold" run warm.
    """
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel

    platform = jax.default_backend()
    on_accel = platform not in ("cpu",)
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    if workload == "resnet50":
        from mxnet_tpu.gluon.model_zoo import vision

        # CPU config leans bigger than the throughput bench's: the warm
        # process pays a fixed ~4s of tracing either way, so the compile
        # share must dominate for the cold/warm ratio to mean anything
        batch, image = (32, 224) if on_accel else (4, 64)
        net = vision.resnet50_v1()
        x = rng.rand(batch, 3, image, image).astype(np.float32)
        y = rng.randint(0, 1000, batch).astype(np.float32)
    elif workload == "bert":
        from mxnet_tpu.gluon.model_zoo import bert

        vocab = 1000
        batch, seqlen = (8, 64) if on_accel else (2, 16)
        inner = bert.bert_small(vocab_size=vocab)

        class MLM(gluon.HybridBlock):
            def __init__(self, net):
                super().__init__()
                self.inner = net

            def hybrid_forward(self, F, toks):
                _, _, logits = self.inner(toks)
                return F.reshape(logits, shape=(-1, vocab))

        net = MLM(inner)
        x = rng.randint(0, vocab, (batch, seqlen)).astype(np.int32)
        y = rng.randint(0, vocab, batch * seqlen).astype(np.float32)
    elif workload == "llama":
        from mxnet_tpu.gluon.model_zoo import llama

        vocab = 512
        batch, seqlen = (8, 64) if on_accel else (2, 16)
        inner = llama.llama_small()

        class LM(gluon.HybridBlock):
            def __init__(self, net):
                super().__init__()
                self.inner = net

            def hybrid_forward(self, F, toks):
                return F.reshape(self.inner(toks), shape=(-1, vocab))

        net = LM(inner)
        x = rng.randint(0, vocab, (batch, seqlen)).astype(np.int32)
        y = rng.randint(0, vocab, batch * seqlen).astype(np.float32)
    else:
        raise SystemExit("unknown cold-probe workload %r" % (workload,))
    net.initialize(mx.init.Xavier())
    step = parallel.JitTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        "sgd", {"learning_rate": 0.1})
    # the warm process exercises BOTH halves of the cold-start fix: the
    # persistent compilation cache (jit retraces, compile comes from
    # disk) and the AOT executable the cold process exported (no trace
    # at all — load_executable + first step is the whole startup)
    cache_dir = os.environ.get("MXNET_COMPILE_CACHE_DIR", "")
    bundle = os.path.join(cache_dir, "%s_step.mxaot" % workload) \
        if cache_dir else ""
    if bundle and os.path.exists(bundle):
        t0 = time.perf_counter()
        step.load_executable(bundle, x, y)
        loss = step.step(x, y)
        jax.block_until_ready(loss)
    else:
        t0 = time.perf_counter()
        loss = step.step(x, y)
        jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    if bundle and not os.path.exists(bundle):
        step.save_executable(bundle)  # untimed: arms the warm process
    _log("%s cold probe (platform=%s): %.3fs loss=%.4f"
         % (workload, platform, dt, float(loss)))
    print("COLD_START_SECONDS=%.3f" % dt, flush=True)


def _probe_subprocess(args, env, marker, label, timeout=900):
    """Re-run THIS script in a fresh interpreter and parse one marker line.

    The shared skeleton of every probe-style benchmark (cold start,
    serving): claims like "compile+first-step in a fresh process" or
    "zero live jits while serving" only mean anything in an interpreter
    that did none of the parent's warmup, so the probe body runs behind
    a ``bench.py --<mode> ...`` re-invocation and reports through a
    single ``MARKER=payload`` stdout line.  Returns the payload string;
    raises with the probe's stderr tail on any failure.
    """
    import subprocess

    script = os.path.abspath(__file__)
    proc = subprocess.run([sys.executable, script] + list(args), env=env,
                          capture_output=True, text=True, timeout=timeout)
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr[-2000:] + "\n")
        raise RuntimeError("%s probe exited %d" % (label, proc.returncode))
    for line in proc.stdout.splitlines():
        if line.startswith(marker):
            return line[len(marker):]
    sys.stderr.write(proc.stderr[-2000:] + "\n")
    raise RuntimeError("%s probe printed no %s line" % (label, marker))


def _run_cold_start(workload):
    """`<workload>_cold_start_seconds`: compile+first-step wall time in a
    FRESH process — the number the persistent compilation cache exists
    to kill (docs/perf.md "cold start").

    Spawns ``--cold-probe <workload>`` twice against ONE empty temp
    cache dir: the first (cold) process pays real XLA compiles and
    populates the cache; the second (warm) process shares the dir and
    should spend ~0 in the compiler.  The metric value is the COLD
    number; the warm number and speedup ride along as extra fields.
    """
    import shutil
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="mxnet-coldstart-")
    env = dict(os.environ)
    env.update({
        "MXNET_COMPILE_CACHE": "1",
        "MXNET_COMPILE_CACHE_DIR": cache_dir,
        "MXNET_COMPILE_CACHE_MIN_SECS": "0",
    })

    def probe(label):
        t0 = time.perf_counter()
        secs = float(_probe_subprocess(
            ["--cold-probe", workload], env, "COLD_START_SECONDS=",
            "%s %s" % (workload, label)))
        _log("%s %s process: %.3fs compile+first step (wall %.1fs)"
             % (workload, label, secs, time.perf_counter() - t0))
        return secs

    try:
        cold = probe("cold")
        warm = probe("warm")
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    return {"value": cold, "warm_seconds": round(warm, 3),
            "cold_warm_speedup": round(cold / warm, 2) if warm > 0 else 0.0}


# serving bench workload: seeded, mixed-length (the length spread is
# what continuous batching exploits and static batching wastes)
_SERVE_N_REQUESTS = 64
_SERVE_WORKLOAD = dict(rate_rps=2000.0, prompt_range=(2, 30),
                       max_new_range=(2, 64), vocab_size=512, seed=0)
# a 64-request replay is sub-second on CPU — shorter than the
# multi-second noisy windows shared-CPU hosts inject.  Every serve
# throughput number is therefore the median of this many identical
# replays, which keeps the workload definition fixed while damping the
# host noise.
_SERVE_REPLAYS = 3


def _median(vals):
    return sorted(vals)[len(vals) // 2]


def _serve_export(path):
    """Subprocess entry (`--serve-export <path>`): AOT-compile the
    llama_small serving bundle.  THIS process pays the jits so the probe
    process can claim zero live compiles."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, serve
    from mxnet_tpu.gluon.model_zoo import llama

    mx.random.seed(0)
    net = llama.llama_small()
    net.initialize()
    net(nd.array(np.zeros((1, 4), np.int32)))
    g = serve.export_serving_bundle(net, path, page_size=8, num_pages=512,
                                    max_batch=8, prefill_buckets=(16, 32))
    _log("serve export: %s" % g.describe())
    print("SERVE_EXPORT_OK", flush=True)


def _serve_probe(path):
    """Subprocess entry (`--serve-probe <bundle>`): measure continuous
    batching against the static baseline IN THE SAME PROCESS.

    Continuous: drive the seeded Poisson workload through the running
    scheduler (drive_workload paces real submit threads' arrivals —
    sleeps are fine here, this is a benchmark, not the unit suite).
    Static: replay the identical request set through static_generate
    (fixed groups, no mid-flight admission, each group at the pace of
    its slowest member) on the same runner and arena — the measured gap
    is pure scheduling.  Both sides report the median of
    ``_SERVE_REPLAYS`` identical replays (see the constant's comment).
    A third pass replays the continuous workload with the runtime lock
    sanitizer installed (MXNET_LOCKCHECK, lint pass 11) so its overhead
    is a tracked number (acceptance: <= 3% off the unproxied rate, like
    the telemetry on/off gate; docs/static_analysis.md), and a fourth
    does the same for the resource-leak sanitizer (MXNET_RESCHECK, lint
    pass 12) under the same <= 3% gate — every request acquires and
    releases one future token plus arena page tokens, so this is the
    sanitizer's worst-case path.
    Also reports the process's live-compile count:
    nonzero means the AOT warm start regressed and the throughput
    numbers are polluted by jit time.
    """
    from mxnet_tpu import serve
    from mxnet_tpu.telemetry import metrics as telemetry_metrics
    from mxnet_tpu.testing import lockcheck, rescheck

    srv = serve.LlamaServer(path).start()
    rates = []
    for _ in range(_SERVE_REPLAYS):
        wl = serve.poisson_workload(_SERVE_N_REQUESTS, **_SERVE_WORKLOAD)
        reqs, wall = serve.drive_workload(srv, wl, timeout=600)
        done = [r for r in reqs if r.error is None]
        rates.append(sum(len(r.tokens) for r in done) / wall)
    srv.stop()
    sched = srv.scheduler

    static_srv = serve.LlamaServer(path)  # NOT started: caller-side loop
    static_rates = []
    for _ in range(_SERVE_REPLAYS):
        static_wl = serve.poisson_workload(_SERVE_N_REQUESTS,
                                           **_SERVE_WORKLOAD)
        t0 = time.perf_counter()
        outs = static_srv.static_generate([req for _, req in static_wl])
        static_rates.append(
            sum(len(t) for t in outs) / (time.perf_counter() - t0))

    # lockcheck overhead: install() only proxies locks created AFTER it
    # runs, so a FRESH server is built under the sanitizer and the
    # identical seeded workload replayed on it.  The continuous number
    # above stays the headline metric; this one rides as an extra.
    lockcheck.install()
    try:
        lc_srv = serve.LlamaServer(path).start()
        lc_rates = []
        for _ in range(_SERVE_REPLAYS):
            lc_wl = serve.poisson_workload(_SERVE_N_REQUESTS,
                                           **_SERVE_WORKLOAD)
            lc_reqs, lc_wall = serve.drive_workload(lc_srv, lc_wl,
                                                    timeout=600)
            lc_done = [r for r in lc_reqs if r.error is None]
            lc_rates.append(sum(len(r.tokens) for r in lc_done) / lc_wall)
        lc_srv.stop()
    finally:
        lockcheck.uninstall()
        lockcheck.reset()

    # rescheck overhead: same fresh-server discipline — install() only
    # tracks handles acquired after it runs, and stop() asserts the
    # tracked scopes quiescent, so a leak anywhere in the replayed
    # workload fails the bench rather than skewing it.
    rescheck.install()
    try:
        rc_srv = serve.LlamaServer(path).start()
        rc_rates = []
        for _ in range(_SERVE_REPLAYS):
            rc_wl = serve.poisson_workload(_SERVE_N_REQUESTS,
                                           **_SERVE_WORKLOAD)
            rc_reqs, rc_wall = serve.drive_workload(rc_srv, rc_wl,
                                                    timeout=600)
            rc_done = [r for r in rc_reqs if r.error is None]
            rc_rates.append(sum(len(r.tokens) for r in rc_done) / rc_wall)
        rc_srv.stop()
    finally:
        rescheck.uninstall()
        rescheck.reset()

    snap = telemetry_metrics.snapshot()
    compiles = sum(s["value"] for s in snap.get(
        "mxnet_compiles_total", {}).get("series", []))
    doc = {
        "continuous_tok_s": round(_median(rates), 2),
        "static_tok_s": round(_median(static_rates), 2),
        "lockcheck_tok_s": round(_median(lc_rates), 2),
        "rescheck_tok_s": round(_median(rc_rates), 2),
        "completed": len(done),
        "n_requests": len(reqs),
        "ttft_p50_ms": round(sched.percentile("ttft", 0.50) * 1e3, 2),
        "ttft_p99_ms": round(sched.percentile("ttft", 0.99) * 1e3, 2),
        "tpot_p50_ms": round(sched.percentile("tpot", 0.50) * 1e3, 3),
        "live_compiles": int(compiles),
    }
    print("SERVE_RESULT=%s" % json.dumps(doc), flush=True)


def _run_serve(platform):
    """`llama_serve_tok_s`: continuous-batching serving throughput over
    the AOT bundle, vs the naive static-batch baseline in the same run.

    Two fresh subprocesses through :func:`_probe_subprocess`:
    ``--serve-export`` compiles the bundle (paying every jit), then
    ``--serve-probe`` serves the seeded mixed-length Poisson workload
    with zero live compiles and measures both schedulers on the same
    runner+arena.  The metric value is continuous tok/s; the static
    number, the speedup, and the TTFT/TPOT percentiles ride along.
    """
    import shutil
    import tempfile

    tmp = tempfile.mkdtemp(prefix="mxnet-serve-bench-")
    try:
        bundle = os.path.join(tmp, "llama_small.mxaot")
        env = dict(os.environ)
        _probe_subprocess(["--serve-export", bundle], env,
                          "SERVE_EXPORT_OK", "serve export")
        doc = json.loads(_probe_subprocess(
            ["--serve-probe", bundle], env, "SERVE_RESULT=", "serve"))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    static = doc["static_tok_s"]
    speedup = round(doc["continuous_tok_s"] / static, 2) if static else 0.0
    cont = doc["continuous_tok_s"]
    lc_overhead = (round((1.0 - doc["lockcheck_tok_s"] / cont) * 100.0, 2)
                   if cont else 0.0)
    rc_overhead = (round((1.0 - doc["rescheck_tok_s"] / cont) * 100.0, 2)
                   if cont else 0.0)
    _log("serve: %.1f tok/s continuous vs %.1f static (%.2fx), "
         "ttft p50/p99 %.1f/%.1f ms, %d/%d completed, %d live compiles, "
         "lockcheck %.1f tok/s (%.1f%% overhead), "
         "rescheck %.1f tok/s (%.1f%% overhead)"
         % (doc["continuous_tok_s"], static, speedup, doc["ttft_p50_ms"],
            doc["ttft_p99_ms"], doc["completed"], doc["n_requests"],
            doc["live_compiles"], doc["lockcheck_tok_s"], lc_overhead,
            doc["rescheck_tok_s"], rc_overhead))
    return {"value": doc["continuous_tok_s"],
            "static_tok_s": static,
            "continuous_vs_static": speedup,
            "ttft_p50_ms": doc["ttft_p50_ms"],
            "ttft_p99_ms": doc["ttft_p99_ms"],
            "tpot_p50_ms": doc["tpot_p50_ms"],
            "completed": doc["completed"],
            "n_requests": doc["n_requests"],
            "live_compiles": doc["live_compiles"],
            "lockcheck_tok_s": doc["lockcheck_tok_s"],
            "lockcheck_overhead_pct": lc_overhead,
            "rescheck_tok_s": doc["rescheck_tok_s"],
            "rescheck_overhead_pct": rc_overhead}


def _serve_spec_export(path):
    """Subprocess entry (`--serve-spec-export <path>`): AOT-compile the
    llama_small serving bundle WITH the ISSUE 13 decode multipliers —
    a compiled spec_k=2 verify signature and an int8 paged-KV arena —
    at the same paging geometry as the plain serve bundle."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, serve
    from mxnet_tpu.gluon.model_zoo import llama

    mx.random.seed(0)
    net = llama.llama_small()
    net.initialize()
    net(nd.array(np.zeros((1, 4), np.int32)))
    # spec_k=2: verify cost grows with the compiled width faster than
    # n-gram acceptance does on this workload (measured on the CPU
    # backend), so the narrow block wins end-to-end
    g = serve.export_serving_bundle(net, path, page_size=8, num_pages=512,
                                    max_batch=8, prefill_buckets=(16, 32),
                                    spec_k=2, kv_dtype="int8")
    _log("serve spec export: %s" % g.describe())
    print("SERVE_SPEC_EXPORT_OK", flush=True)


def _serve_spec_probe(path):
    """Subprocess entry (`--serve-spec-probe <bundle>`): speculative vs
    plain decode on the SAME int8 bundle, same seeded workload.

    Spec-on serves the 64-request Poisson workload with the n-gram
    proposer feeding the compiled verify signature; spec-off replays the
    identical workload through the same bundle with runtime spec_k=0
    (plain decode path).  Greedy acceptance is exact, so the two runs
    must produce token-for-token identical streams — asserted here, in
    the same process that reports the speedup.  Each side's throughput
    is the median of ``_SERVE_REPLAYS`` identical replays (see the
    constant's comment).  Also reports the n-gram
    acceptance rate, the kv_page device bytes vs an fp32 arena at
    identical geometry, and the live-compile count (must stay 0)."""
    from mxnet_tpu import serve
    from mxnet_tpu.serve.model import KVGeometry
    from mxnet_tpu.telemetry import metrics as telemetry_metrics

    srv = serve.LlamaServer(path).start()
    rates, reqs = [], None
    for _ in range(_SERVE_REPLAYS):
        wl = serve.poisson_workload(_SERVE_N_REQUESTS, **_SERVE_WORKLOAD)
        run_reqs, wall = serve.drive_workload(srv, wl, timeout=600)
        done = [r for r in run_reqs if r.error is None]
        rates.append(sum(len(r.tokens) for r in done) / wall)
        reqs = reqs if reqs is not None else run_reqs
    st = srv.stats()
    srv.stop()
    kv_bytes_int8 = sum(int(b.nbytes) for b in srv.arena.buffers())

    off_srv = serve.LlamaServer(path, spec_k=0).start()
    off_rates, off_reqs = [], None
    for _ in range(_SERVE_REPLAYS):
        off_wl = serve.poisson_workload(_SERVE_N_REQUESTS,
                                        **_SERVE_WORKLOAD)
        run_reqs, off_wall = serve.drive_workload(off_srv, off_wl,
                                                  timeout=600)
        off_done = [r for r in run_reqs if r.error is None]
        off_rates.append(sum(len(r.tokens) for r in off_done) / off_wall)
        off_reqs = off_reqs if off_reqs is not None else run_reqs

    off_srv.stop()

    mismatched = sum(
        1 for a, b in zip(reqs, off_reqs)
        if a.error is None and b.error is None and a.tokens != b.tokens)
    if mismatched:
        raise AssertionError(
            "speculative decoding changed %d/%d request token streams "
            "vs spec-off on the same bundle" % (mismatched, len(reqs)))

    g32 = KVGeometry(**dict(srv.geometry.to_dict(),
                            kv_dtype=srv.geometry.dtype))
    kv_bytes_fp32 = sum(int(b.nbytes)
                        for b in serve.PagedKVArena(g32).buffers())

    snap = telemetry_metrics.snapshot()
    compiles = sum(s["value"] for s in snap.get(
        "mxnet_compiles_total", {}).get("series", []))
    parity_ok = sum(1 for r in reqs if r.error is None)
    doc = {
        "spec_tok_s": round(_median(rates), 2),
        "spec_off_tok_s": round(_median(off_rates), 2),
        "parity_checked": parity_ok,
        "completed": parity_ok,
        "n_requests": len(reqs),
        "accept_rate": round(st["spec_accept_rate"], 4),
        "spec_accepted_tokens": int(st["spec_accepted_tokens"]),
        "kv_bytes_int8": kv_bytes_int8,
        "kv_bytes_fp32": kv_bytes_fp32,
        "kv_bytes_ratio": round(kv_bytes_int8 / kv_bytes_fp32, 4),
        "live_compiles": int(compiles),
    }
    print("SERVE_SPEC_RESULT=%s" % json.dumps(doc), flush=True)


def _run_serve_spec(platform):
    """`llama_serve_spec_tok_s`: n-gram speculative decoding over the
    int8-KV AOT bundle, on the same 64-request Poisson workload as
    `llama_serve_tok_s`.

    Two fresh subprocesses: ``--serve-spec-export`` compiles the
    spec_k=2 / int8 bundle (paying every jit), then
    ``--serve-spec-probe`` serves the workload spec-on and spec-off on
    the same bundle with token-for-token parity asserted between the
    two runs.  The metric value is spec-on tok/s; the spec-off
    baseline, acceptance rate, and the int8/fp32 kv_page byte ratio
    ride along."""
    import shutil
    import tempfile

    tmp = tempfile.mkdtemp(prefix="mxnet-serve-spec-bench-")
    try:
        bundle = os.path.join(tmp, "llama_small_spec.mxaot")
        env = dict(os.environ)
        _probe_subprocess(["--serve-spec-export", bundle], env,
                          "SERVE_SPEC_EXPORT_OK", "serve spec export")
        doc = json.loads(_probe_subprocess(
            ["--serve-spec-probe", bundle], env, "SERVE_SPEC_RESULT=",
            "serve spec"))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    off = doc["spec_off_tok_s"]
    speedup = round(doc["spec_tok_s"] / off, 2) if off else 0.0
    _log("serve spec: %.1f tok/s spec-on vs %.1f spec-off (%.2fx), "
         "accept rate %.2f, kv bytes int8/fp32 %.2f, %d/%d completed, "
         "%d live compiles"
         % (doc["spec_tok_s"], off, speedup, doc["accept_rate"],
            doc["kv_bytes_ratio"], doc["completed"], doc["n_requests"],
            doc["live_compiles"]))
    return {"value": doc["spec_tok_s"],
            "spec_off_tok_s": off,
            "spec_vs_off": speedup,
            "accept_rate": doc["accept_rate"],
            "spec_accepted_tokens": doc["spec_accepted_tokens"],
            "parity_checked": doc["parity_checked"],
            "kv_bytes_int8": doc["kv_bytes_int8"],
            "kv_bytes_fp32": doc["kv_bytes_fp32"],
            "kv_bytes_ratio": doc["kv_bytes_ratio"],
            "completed": doc["completed"],
            "n_requests": doc["n_requests"],
            "live_compiles": doc["live_compiles"]}


def _serve_paged_export(dirpath):
    """Subprocess entry (`--serve-paged-export <dir>`): AOT-compile TWO
    llama_small serving bundles from the SAME seeded net at the SAME
    spec_k=2 / int8 paging geometry — one with the paged-attention
    kernel baked in (``paged_kernel="1"``: compiled Pallas on TPU, the
    interpreter trace elsewhere) and one on the gather + grouped-einsum
    reference (``"0"``).  The choice lives in the bundle's geometry
    meta, so the probe process picks a path by picking a file."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, serve
    from mxnet_tpu.gluon.model_zoo import llama

    mx.random.seed(0)
    net = llama.llama_small()
    net.initialize()
    net(nd.array(np.zeros((1, 4), np.int32)))
    for mode, fname in (("1", "paged_on.mxaot"), ("0", "paged_off.mxaot")):
        g = serve.export_serving_bundle(
            net, os.path.join(dirpath, fname), page_size=8, num_pages=512,
            max_batch=8, prefill_buckets=(16, 32), spec_k=2,
            kv_dtype="int8", paged_kernel=mode)
        assert g.paged_kernel == mode, g.describe()
        _log("serve paged export (%s): %s" % (fname, g.describe()))
    print("SERVE_PAGED_EXPORT_OK", flush=True)


def _serve_paged_probe(dirpath):
    """Subprocess entry (`--serve-paged-probe <dir>`): kernel-on vs
    kernel-off on the same seeded workload, token parity asserted here.

    Serves the 64-request Poisson workload through the ``paged_on``
    bundle, then through ``paged_off``; greedy decoding means the two
    bundles must emit token-for-token identical streams — asserted in
    this process, so a parity break zeroes the metric instead of
    shipping a wrong speedup.  Each side is the median of
    ``_SERVE_REPLAYS`` replays.  The memdump peak watermark is reset
    between the sides: the on/off byte ratio is the kernel's HBM story
    (the reference gathers + dequantizes every lane's full context per
    step; the kernel streams page tiles)."""
    from mxnet_tpu import serve
    from mxnet_tpu.telemetry import memdump
    from mxnet_tpu.telemetry import metrics as telemetry_metrics

    def one_side(fname):
        memdump.reset()
        srv = serve.LlamaServer(os.path.join(dirpath, fname)).start()
        rates, reqs = [], None
        for _ in range(_SERVE_REPLAYS):
            wl = serve.poisson_workload(_SERVE_N_REQUESTS,
                                        **_SERVE_WORKLOAD)
            run_reqs, wall = serve.drive_workload(srv, wl, timeout=600)
            done = [r for r in run_reqs if r.error is None]
            rates.append(sum(len(r.tokens) for r in done) / wall)
            reqs = reqs if reqs is not None else run_reqs
        srv.stop()
        memdump.refresh()
        return _median(rates), reqs, int(memdump.peak_bytes())

    on_rate, on_reqs, on_peak = one_side("paged_on.mxaot")
    off_rate, off_reqs, off_peak = one_side("paged_off.mxaot")

    mismatched = sum(
        1 for a, b in zip(on_reqs, off_reqs)
        if a.error is None and b.error is None and a.tokens != b.tokens)
    if mismatched:
        raise AssertionError(
            "paged-attention kernel changed %d/%d request token streams "
            "vs the reference path" % (mismatched, len(on_reqs)))

    snap = telemetry_metrics.snapshot()
    compiles = sum(s["value"] for s in snap.get(
        "mxnet_compiles_total", {}).get("series", []))
    parity_ok = sum(1 for r in on_reqs if r.error is None)
    doc = {
        "paged_tok_s": round(on_rate, 2),
        "paged_off_tok_s": round(off_rate, 2),
        "parity_checked": parity_ok,
        "completed": parity_ok,
        "n_requests": len(on_reqs),
        "paged_peak_bytes": on_peak,
        "ref_peak_bytes": off_peak,
        "paged_attn_hbm_bytes_ratio":
            round(on_peak / off_peak, 4) if off_peak else 0.0,
        "live_compiles": int(compiles),
    }
    print("SERVE_PAGED_RESULT=%s" % json.dumps(doc), flush=True)


def _run_serve_paged(platform):
    """`llama_serve_paged_tok_s`: the paged-attention decode kernel vs
    the gather + grouped-einsum reference, same int8/spec_k=2 bundle
    geometry, same 64-request Poisson workload as `llama_serve_tok_s`.

    Two fresh subprocesses: ``--serve-paged-export`` compiles BOTH
    bundles (kernel choice is baked at export, recorded in geometry
    meta), then ``--serve-paged-probe`` serves the workload through
    each with token parity asserted between the sides.  The metric
    value is kernel-on tok/s; the kernel-off baseline and the memdump
    peak-byte ratio ride along.  Off-TPU the "kernel" side is the
    interpreter trace (CI parity path), so the CPU number is a
    correctness canary, not the TPU speedup."""
    import shutil
    import tempfile

    tmp = tempfile.mkdtemp(prefix="mxnet-serve-paged-bench-")
    env = dict(os.environ)
    try:
        _probe_subprocess(["--serve-paged-export", tmp], env,
                          "SERVE_PAGED_EXPORT_OK", "serve paged export")
        doc = json.loads(_probe_subprocess(
            ["--serve-paged-probe", tmp], env, "SERVE_PAGED_RESULT=",
            "serve paged"))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    off = doc["paged_off_tok_s"]
    speedup = round(doc["paged_tok_s"] / off, 2) if off else 0.0
    _log("serve paged: %.1f tok/s kernel-on vs %.1f kernel-off (%.2fx), "
         "peak bytes on/off %.2f, %d/%d completed, %d live compiles"
         % (doc["paged_tok_s"], off, speedup,
            doc["paged_attn_hbm_bytes_ratio"], doc["completed"],
            doc["n_requests"], doc["live_compiles"]))
    return {"value": doc["paged_tok_s"],
            "paged_off_tok_s": off,
            "paged_vs_off": speedup,
            "parity_checked": doc["parity_checked"],
            "paged_peak_bytes": doc["paged_peak_bytes"],
            "ref_peak_bytes": doc["ref_peak_bytes"],
            "paged_attn_hbm_bytes_ratio":
                doc["paged_attn_hbm_bytes_ratio"],
            "completed": doc["completed"],
            "n_requests": doc["n_requests"],
            "live_compiles": doc["live_compiles"]}


# prefix-cache bench workload: the chat-service shape the radix cache
# exists for — most requests open with the SAME long system prompt
_PREFIX_SYSTEM_TOKENS = 2048
_PREFIX_SHARE = 0.8


def _serve_prefix_export(path):
    """Subprocess entry (`--serve-prefix-export <path>`): AOT-compile
    the llama_small bundle for the prefix-cache bench.  Chunked prefill
    (``prefill_chunk=32``) is what makes the 2k system prompt servable
    at all here: the bucket ladder stops at 32, so over-bucket prompts
    prefill in fixed-shape chunks and the radix cache splices everything
    but the per-request tail.  The arena is sized so the CACHE-OFF side
    can hold a full batch of unshared 2k contexts — the comparison must
    measure splicing, not cache-off page starvation."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, serve
    from mxnet_tpu.gluon.model_zoo import llama

    mx.random.seed(0)
    net = llama.llama_small()
    net.initialize()
    net(nd.array(np.zeros((1, 4), np.int32)))
    g = serve.export_serving_bundle(net, path, page_size=16,
                                    num_pages=1400, max_batch=8,
                                    prefill_buckets=(16, 32),
                                    prefill_chunk=32)
    _log("serve prefix export: %s" % g.describe())
    print("SERVE_PREFIX_EXPORT_OK", flush=True)


def _prefix_workload(seed=0):
    """Seeded 64-request workload: 80% open with the same 2048-token
    system prompt plus a short unique tail, 20% are fully unique.
    Returns ``[(arrival_s, Request, is_shared)]``."""
    from mxnet_tpu.serve import Request

    rng = np.random.default_rng(seed)
    system = rng.integers(0, 512, size=_PREFIX_SYSTEM_TOKENS).tolist()
    arrivals = np.cumsum(rng.exponential(1.0 / 2000.0,
                                         size=_SERVE_N_REQUESTS))
    out = []
    for i in range(_SERVE_N_REQUESTS):
        shared = bool(rng.random() < _PREFIX_SHARE)
        if shared:
            tail = rng.integers(0, 512,
                                size=int(rng.integers(4, 9))).tolist()
            prompt = system + tail
        else:
            prompt = rng.integers(0, 512,
                                  size=int(rng.integers(16, 33))).tolist()
        out.append((float(arrivals[i]),
                    Request(prompt, max_new_tokens=int(
                        rng.integers(4, 9))), shared))
    return out


def _serve_prefix_probe(path):
    """Subprocess entry (`--serve-prefix-probe <bundle>`): radix prefix
    cache on vs off on the SAME bundle, same seeded shared-prefix
    workload, token-for-token parity asserted here.

    Each side replays the workload ``_SERVE_REPLAYS`` times on a FRESH
    server (cold cache every replay, so the cache-on numbers include
    the first request's cold miss) and reports the median.  The TTFT
    split is the headline latency story: cache-on shared requests after
    the first (splice + tail-only prefill) vs cache-off shared requests
    (full 2k chunked prefill).  Greedy decoding plus the arena purity
    invariant mean the two sides must emit identical streams — a parity
    break zeroes the metric instead of shipping a wrong speedup.  The
    process must perform zero live compiles."""
    from mxnet_tpu import serve
    from mxnet_tpu.telemetry import metrics as telemetry_metrics

    def one_side(cache_on):
        os.environ["MXNET_SERVE_PREFIX_CACHE"] = "1" if cache_on else "0"
        rates, shared_ttfts, streams, stats = [], [], None, None
        for _ in range(_SERVE_REPLAYS):
            srv = serve.LlamaServer(path).start()  # fresh: cold cache
            wl = _prefix_workload(seed=0)
            reqs, wall = serve.drive_workload(
                srv, [(a, r) for a, r, _ in wl], timeout=600)
            done = [r for r in reqs if r.error is None]
            rates.append(sum(len(r.tokens) for r in done) / wall)
            shared_done = [r for _, r, s in wl
                           if s and r.error is None
                           and r.first_token_t is not None]
            # the first shared request pays the cold miss that fills
            # the cache: it belongs to the cold sample, not the cached
            sample = shared_done[1:] if cache_on else shared_done
            shared_ttfts.extend(r.first_token_t - r.submit_t
                                for r in sample)
            if streams is None:
                streams = [list(r.tokens) for r in reqs]
            stats = srv.stats()
            srv.stop()
        return _median(rates), shared_ttfts, streams, stats

    on_rate, on_ttfts, on_streams, on_stats = one_side(True)
    off_rate, off_ttfts, off_streams, _ = one_side(False)

    mismatched = sum(1 for a, b in zip(on_streams, off_streams)
                     if a != b)
    if mismatched:
        raise AssertionError(
            "prefix cache changed %d/%d request token streams vs "
            "cache-off on the same bundle"
            % (mismatched, len(on_streams)))

    snap = telemetry_metrics.snapshot()
    compiles = sum(s["value"] for s in snap.get(
        "mxnet_compiles_total", {}).get("series", []))

    def p50(vals):
        return sorted(vals)[len(vals) // 2] if vals else 0.0

    doc = {
        "prefix_tok_s": round(on_rate, 2),
        "prefix_off_tok_s": round(off_rate, 2),
        "hit_rate": round(on_stats["prefix_hit_rate"], 4),
        "cached_tokens": int(on_stats["prefix_cached_tokens"]),
        "ttft_cached_p50_ms": round(p50(on_ttfts) * 1e3, 2),
        "ttft_cold_p50_ms": round(p50(off_ttfts) * 1e3, 2),
        "parity_checked": len(on_streams),
        "completed": sum(1 for t in on_streams if t),
        "n_requests": _SERVE_N_REQUESTS,
        "live_compiles": int(compiles),
    }
    print("SERVE_PREFIX_RESULT=%s" % json.dumps(doc), flush=True)


def _run_serve_prefix(platform):
    """`llama_serve_prefix_tok_s`: cross-request KV reuse (ISSUE 19) on
    a shared-prefix workload — 64 requests, 80% opening with the same
    2048-token system prompt — cache-on vs cache-off on the same
    bundle.

    Two fresh subprocesses: ``--serve-prefix-export`` compiles the
    chunk-capable bundle (paying every jit), then
    ``--serve-prefix-probe`` serves the workload both ways with token
    parity asserted between the sides.  The metric value is cache-on
    tok/s; the off baseline, the hit rate, and the cached-vs-cold TTFT
    p50 split ride along."""
    import shutil
    import tempfile

    tmp = tempfile.mkdtemp(prefix="mxnet-serve-prefix-bench-")
    try:
        bundle = os.path.join(tmp, "llama_small_prefix.mxaot")
        env = dict(os.environ)
        env.pop("MXNET_SERVE_PREFIX_CACHE", None)  # probe owns the knob
        _probe_subprocess(["--serve-prefix-export", bundle], env,
                          "SERVE_PREFIX_EXPORT_OK", "serve prefix export")
        doc = json.loads(_probe_subprocess(
            ["--serve-prefix-probe", bundle], env, "SERVE_PREFIX_RESULT=",
            "serve prefix"))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    off = doc["prefix_off_tok_s"]
    speedup = round(doc["prefix_tok_s"] / off, 2) if off else 0.0
    cached = doc["ttft_cached_p50_ms"]
    ttft_speedup = (round(doc["ttft_cold_p50_ms"] / cached, 2)
                    if cached else 0.0)
    _log("serve prefix: %.1f tok/s cache-on vs %.1f cache-off (%.2fx), "
         "hit rate %.2f, ttft p50 cached/cold %.1f/%.1f ms (%.1fx), "
         "%d/%d completed, %d live compiles"
         % (doc["prefix_tok_s"], off, speedup, doc["hit_rate"],
            doc["ttft_cached_p50_ms"], doc["ttft_cold_p50_ms"],
            ttft_speedup, doc["completed"], doc["n_requests"],
            doc["live_compiles"]))
    return {"value": doc["prefix_tok_s"],
            "prefix_off_tok_s": off,
            "prefix_vs_off": speedup,
            "hit_rate": doc["hit_rate"],
            "cached_tokens": doc["cached_tokens"],
            "ttft_cached_p50_ms": doc["ttft_cached_p50_ms"],
            "ttft_cold_p50_ms": doc["ttft_cold_p50_ms"],
            "ttft_cached_vs_cold": ttft_speedup,
            "parity_checked": doc["parity_checked"],
            "completed": doc["completed"],
            "n_requests": doc["n_requests"],
            "live_compiles": doc["live_compiles"]}


def _fleet_probe(path):
    """Subprocess entry (`--fleet-probe <bundle>`): fleet-front serving
    throughput over N=3 in-process replicas of the SAME AOT bundle.

    The seeded 64-request Poisson workload is replayed through a
    ``FleetRouter`` (queue-aware power-of-two routing, live prober) via
    ``fleet_drive_workload`` — the fleet twin of the `serve` bench.
    Aggregate tok/s is the headline; TTFT p99 across the fleet rides
    along.  A second pass measures the ROUTING TAX: the same workload
    through a router fronting ONE replica vs directly through that
    replica's scheduler (acceptance: within 5%).  A third pass measures
    the OBSERVABILITY TAX: the same 3-replica fleet with telemetry +
    flight recorder disabled (acceptance: on/off gap <= 3%, the
    standing gate from docs/observability.md).  The process must
    perform zero live compiles — nonzero means the AOT warm start
    regressed and every number here is polluted by jit time."""
    from mxnet_tpu import serve
    from mxnet_tpu.telemetry import metrics as telemetry_metrics

    def fleet_rates(n_replicas):
        servers = [serve.LlamaServer(path).start()
                   for _ in range(n_replicas)]
        router = serve.FleetRouter(servers, probe_interval=0.2, seed=0)
        router.start()
        rates, ttfts, futs = [], [], None
        try:
            for _ in range(_SERVE_REPLAYS):
                wl = serve.poisson_workload(_SERVE_N_REQUESTS,
                                            **_SERVE_WORKLOAD)
                run_futs, wall = serve.fleet_drive_workload(router, wl,
                                                            timeout=600)
                done = [f for f in run_futs if f.error is None]
                rates.append(sum(len(f.tokens) for f in done) / wall)
                ttfts.extend(f.ttft for f in done if f.ttft is not None)
                futs = futs if futs is not None else run_futs
        finally:
            router.stop()
            for srv in servers:
                srv.drain(timeout=60)
                srv.stop()
        stats = router.healthz()
        p99 = sorted(ttfts)[int(0.99 * (len(ttfts) - 1))] if ttfts else 0.0
        return _median(rates), p99, futs, stats

    fleet_rate, ttft_p99, futs, stats = fleet_rates(3)

    # routing tax at N=1: the router's pick/retry machinery + future
    # thread vs the same replica driven directly
    direct_srv = serve.LlamaServer(path).start()
    direct_rates = []
    for _ in range(_SERVE_REPLAYS):
        wl = serve.poisson_workload(_SERVE_N_REQUESTS, **_SERVE_WORKLOAD)
        reqs, wall = serve.drive_workload(direct_srv, wl, timeout=600)
        done = [r for r in reqs if r.error is None]
        direct_rates.append(sum(len(r.tokens) for r in done) / wall)
    direct_srv.stop()
    direct_rate = _median(direct_rates)

    router1_rate, _, _, _ = fleet_rates(1)
    overhead_pct = (round((1.0 - router1_rate / direct_rate) * 100.0, 2)
                    if direct_rate else 0.0)

    # compile census BEFORE the observability-off pass: a disabled
    # registry records nothing, so this snapshot covers every pass that
    # could have compiled (all replicas load the same warm bundle)
    snap = telemetry_metrics.snapshot()
    compiles = sum(s["value"] for s in snap.get(
        "mxnet_compiles_total", {}).get("series", []))

    # OBSERVABILITY TAX: the same 3-replica fleet with metrics + flight
    # recorder OFF — the fleet twin of dispatch_eager_notelemetry, and
    # the number the standing <=3% observability-overhead gate tracks
    from mxnet_tpu import telemetry as _telemetry
    from mxnet_tpu.telemetry import flight as _flight
    was_on, flight_on = _telemetry.enabled(), _flight.enabled()
    _telemetry.disable()
    _flight.disable()
    try:
        notel_rate, _, _, _ = fleet_rates(3)
    finally:
        if was_on:
            _telemetry.enable()
        if flight_on:
            _flight.enable()
    obs_overhead_pct = (round((1.0 - fleet_rate / notel_rate) * 100.0, 2)
                        if notel_rate else 0.0)

    completed = len([f for f in futs if f.error is None])
    doc = {
        "fleet_tok_s": round(fleet_rate, 2),
        "n_replicas": 3,
        "ttft_p99_ms": round(ttft_p99 * 1e3, 2),
        "completed": completed,
        "n_requests": len(futs),
        "retried": stats["retried"],
        "ejections": stats["ejections"],
        "dropped": stats["dropped"],
        "direct_tok_s": round(direct_rate, 2),
        "router1_tok_s": round(router1_rate, 2),
        "routing_overhead_pct": overhead_pct,
        "fleet_notelemetry_tok_s": round(notel_rate, 2),
        "obs_overhead_pct": obs_overhead_pct,
        "live_compiles": int(compiles),
    }
    print("FLEET_RESULT=%s" % json.dumps(doc), flush=True)


def _run_fleet(platform):
    """`fleet_serve_tok_s`: aggregate continuous-batching throughput of
    a 3-replica fleet behind the ISSUE 18 FleetRouter, on the same
    seeded 64-request Poisson workload as `llama_serve_tok_s`.

    Two fresh subprocesses: ``--serve-export`` compiles the one bundle
    every replica loads (paying every jit), then ``--fleet-probe``
    serves the workload through the router with zero live compiles.
    The metric value is fleet-aggregate tok/s; the N=1 router-vs-direct
    routing overhead (acceptance: within 5%) and the fleet TTFT p99
    ride along."""
    import shutil
    import tempfile

    tmp = tempfile.mkdtemp(prefix="mxnet-fleet-bench-")
    try:
        bundle = os.path.join(tmp, "llama_small.mxaot")
        env = dict(os.environ)
        _probe_subprocess(["--serve-export", bundle], env,
                          "SERVE_EXPORT_OK", "fleet export")
        doc = json.loads(_probe_subprocess(
            ["--fleet-probe", bundle], env, "FLEET_RESULT=", "fleet"))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    _log("fleet: %.1f tok/s over %d replicas, ttft p99 %.1f ms, "
         "%d/%d completed (%d retried, %d ejections, %d dropped), "
         "routing overhead %.1f%% (router@1 %.1f vs direct %.1f tok/s), "
         "observability overhead %.1f%% (vs %.1f tok/s with telemetry "
         "off), %d live compiles"
         % (doc["fleet_tok_s"], doc["n_replicas"], doc["ttft_p99_ms"],
            doc["completed"], doc["n_requests"], doc["retried"],
            doc["ejections"], doc["dropped"],
            doc["routing_overhead_pct"], doc["router1_tok_s"],
            doc["direct_tok_s"], doc["obs_overhead_pct"],
            doc["fleet_notelemetry_tok_s"], doc["live_compiles"]))
    return {"value": doc["fleet_tok_s"],
            "n_replicas": doc["n_replicas"],
            "ttft_p99_ms": doc["ttft_p99_ms"],
            "completed": doc["completed"],
            "n_requests": doc["n_requests"],
            "retried": doc["retried"],
            "ejections": doc["ejections"],
            "dropped": doc["dropped"],
            "direct_tok_s": doc["direct_tok_s"],
            "router1_tok_s": doc["router1_tok_s"],
            "routing_overhead_pct": doc["routing_overhead_pct"],
            "fleet_notelemetry_tok_s": doc["fleet_notelemetry_tok_s"],
            "obs_overhead_pct": doc["obs_overhead_pct"],
            "live_compiles": doc["live_compiles"]}


def _run_planner(platform):
    """`python bench.py planner`: wall-clock seconds for one auto-sharding
    plan of the llama_small parameter tree on an abstract 4x2 mesh
    (docs/sharding.md "auto rules").  Pure host-side static analysis —
    no devices, no compiles — so the number is the `rules="auto"` tax a
    training run pays at first step.  LOWER is better; one warm-up plan
    absorbs import/bytecode costs, then the median of 10 runs is
    reported."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, planner
    from mxnet_tpu.gluon.model_zoo import llama

    mx.random.seed(0)
    net = llama.llama_small()
    net.initialize(mx.init.Xavier())
    net(nd.array([[1, 2, 3, 4]], dtype="int32"))  # resolve deferred shapes
    params = [(p.name, tuple(p.shape), str(p.dtype or "float32"))
              for p in net.collect_params().values()]
    axes = {"data": 4, "model": 2}

    def one_plan():
        t0 = time.perf_counter()
        pl = planner.plan(params, axes, step_tokens=128, optimizer_slots=1)
        dt = time.perf_counter() - t0
        assert pl.feasible, pl.explain()
        return dt

    one_plan()  # warm-up
    times = sorted(one_plan() for _ in range(10))
    secs = times[len(times) // 2]
    _log("planner: llama_small on 4x2 planned in %.4fs" % secs)
    # the headline value rounds to 2 decimals (a sub-centisecond plan
    # would read 0.00 — the failure sentinel); planner_ms keeps precision
    return {"value": secs, "planner_ms": round(secs * 1e3, 3),
            "n_params": len(params)}


def _run_cold_resnet50(platform):
    return _run_cold_start("resnet50")


def _run_cold_bert(platform):
    return _run_cold_start("bert")


def _run_cold_llama(platform):
    return _run_cold_start("llama")


_SPECS = {
    # name -> (runner, metric, unit, baseline or None)
    "train": (_run, "resnet50_train_throughput", "images/sec",
              BASELINE_IMG_S),
    "infer": (_run_infer, "resnet50_infer_throughput", "images/sec",
              BASELINE_INFER_FP16),
    "bert": (_run_bert, "bert_base_train_throughput", "samples/sec", None),
    "llama": (_run_llama, "llama_decoder_train_throughput", "tokens/sec",
              None),
    "dispatch_eager": (_run_dispatch_eager, "imperative_dispatch_eager",
                       "ops/sec", None),
    "dispatch_eager_notelemetry": (
        _run_dispatch_eager_notelemetry,
        "imperative_dispatch_eager_notelemetry", "ops/sec", None),
    "dispatch_bulked": (_run_dispatch_bulked, "imperative_dispatch_bulked",
                        "ops/sec", None),
    "dispatch_bulked_train": (
        _run_dispatch_bulked_train, "imperative_dispatch_bulked_train",
        "ops/sec", None),
    "dispatch_bulked_long": (
        _run_dispatch_bulked_long, "imperative_dispatch_bulked_long",
        "ops/sec", None),
    # cold-start seconds: LOWER is better (the other metrics are rates);
    # value is the cold-process number, warm_seconds/cold_warm_speedup
    # ride along as extra record fields
    "cold_resnet50": (_run_cold_resnet50, "resnet50_cold_start_seconds",
                      "seconds", None),
    "cold_bert": (_run_cold_bert, "bert_cold_start_seconds", "seconds",
                  None),
    "cold_llama": (_run_cold_llama, "llama_cold_start_seconds", "seconds",
                   None),
    # serving throughput: value is continuous-batching tok/s; the static
    # baseline, speedup and TTFT percentiles ride along as extra fields
    "serve": (_run_serve, "llama_serve_tok_s", "tokens/sec", None),
    "serve_spec": (_run_serve_spec, "llama_serve_spec_tok_s",
                   "tokens/sec", None),
    # paged-attention kernel vs reference on the same workload; value is
    # kernel-on tok/s, the off baseline + memdump byte ratio ride along
    "serve_paged": (_run_serve_paged, "llama_serve_paged_tok_s",
                    "tokens/sec", None),
    # radix prefix cache on vs off on a shared-prefix workload; value is
    # cache-on tok/s, the off baseline + hit rate + TTFT split ride along
    "prefix": (_run_serve_prefix, "llama_serve_prefix_tok_s",
               "tokens/sec", None),
    # fleet front over 3 replicas of the same bundle; value is aggregate
    # tok/s, the N=1 routing-overhead comparison rides along
    "fleet": (_run_fleet, "fleet_serve_tok_s", "tokens/sec", None),
    # auto-sharding planner latency: pure host-side static analysis,
    # LOWER is better (it is the rules="auto" first-step tax)
    "planner": (_run_planner, "planner_seconds", "seconds", None),
}


def _measure(name, platform, fallback):
    """Run one benchmark; always returns a JSON-able record.

    One retry after a short pause: the remote-compile tunnel can throw
    transient server-side errors (observed: HTTP 500 from the compile
    helper zeroing an otherwise-healthy run's headline metric) — a
    second attempt distinguishes a flaky service from a real failure.
    """
    runner, metric, unit, baseline = _SPECS[name]
    value = 0.0
    for attempt in (1, 2):
        try:
            value = runner(platform)
            break
        except Exception:
            traceback.print_exc(file=sys.stderr)
            if attempt == 1:
                _log("%s benchmark failed; retrying once" % name)
                time.sleep(15)
            else:
                _log("%s benchmark failed twice; emitting value 0" % name)
    extra = {}
    if isinstance(value, dict):  # cold-start runners return value+extras
        extra = {k: v for k, v in value.items() if k != "value"}
        value = value["value"]
    rec = {
        "metric": metric,
        "value": round(value, 2),
        "unit": unit,
        "vs_baseline": round(value / baseline, 3) if baseline else 0.0,
        "platform": platform,
        "fallback": fallback,
        "peak_device_bytes": _peak_device_bytes(),
    }
    rec.update(extra)
    return rec


def _peak_device_bytes():
    """High-water mark of live device bytes at record time (0 if the
    accounting layer is unavailable — the record schema stays stable)."""
    try:
        from mxnet_tpu.telemetry import memdump

        memdump.refresh()
        return int(memdump.peak_bytes())
    except Exception:
        return 0


def main():
    if len(sys.argv) >= 3 and sys.argv[1] == "--cold-probe":
        _cold_probe(sys.argv[2])  # subprocess mode: no _init_backend
        return
    if len(sys.argv) >= 3 and sys.argv[1] == "--serve-export":
        _serve_export(sys.argv[2])  # subprocess mode: pays the AOT jits
        return
    if len(sys.argv) >= 3 and sys.argv[1] == "--serve-probe":
        _serve_probe(sys.argv[2])  # subprocess mode: zero live compiles
        return
    if len(sys.argv) >= 3 and sys.argv[1] == "--serve-spec-export":
        _serve_spec_export(sys.argv[2])  # subprocess: spec_k=4/int8 jits
        return
    if len(sys.argv) >= 3 and sys.argv[1] == "--serve-spec-probe":
        _serve_spec_probe(sys.argv[2])  # subprocess: spec on/off + parity
        return
    if len(sys.argv) >= 3 and sys.argv[1] == "--serve-paged-export":
        _serve_paged_export(sys.argv[2])  # subprocess: kernel + ref jits
        return
    if len(sys.argv) >= 3 and sys.argv[1] == "--serve-paged-probe":
        _serve_paged_probe(sys.argv[2])  # subprocess: on/off + parity
        return
    if len(sys.argv) >= 3 and sys.argv[1] == "--serve-prefix-export":
        _serve_prefix_export(sys.argv[2])  # subprocess: chunk-bundle jits
        return
    if len(sys.argv) >= 3 and sys.argv[1] == "--serve-prefix-probe":
        _serve_prefix_probe(sys.argv[2])  # subprocess: cache on/off+parity
        return
    if len(sys.argv) >= 3 and sys.argv[1] == "--fleet-probe":
        _fleet_probe(sys.argv[2])  # subprocess: 3-replica fleet front
        return
    t_start = time.perf_counter()
    requested = [a for a in sys.argv[1:] if a in _SPECS and a != "train"]
    try:
        platform, fallback = _init_backend()
    except Exception:
        traceback.print_exc(file=sys.stderr)
        platform, fallback = "unknown", True

    if requested:  # single-metric mode: `bench.py bert|infer|llama`
        print(json.dumps(_measure(requested[0], platform, fallback)))
        return

    # Default mode: the headline ResNet-50 train number PLUS every
    # secondary metric, all in ONE JSON line (the driver records the
    # line verbatim; secondaries ride in "metrics" so one artifact
    # carries chip evidence for the full headline set).  A time budget
    # keeps a cold-cache run bounded: secondaries are skipped — and
    # recorded as skipped — once the budget is spent.
    budget = float(os.environ.get("MXNET_BENCH_BUDGET", "2700"))
    head = _measure("train", platform, fallback)
    metrics = [head]
    for name in ("infer", "bert", "llama", "dispatch_eager",
                 "dispatch_eager_notelemetry", "dispatch_bulked",
                 "dispatch_bulked_train", "dispatch_bulked_long",
                 "serve", "serve_spec", "serve_paged", "prefix", "fleet",
                 "planner",
                 "cold_resnet50", "cold_bert",
                 "cold_llama"):
        elapsed = time.perf_counter() - t_start
        if elapsed > budget:
            _log("budget %.0fs spent (%.0fs elapsed); skipping %s"
                 % (budget, elapsed, name))
            metrics.append({
                "metric": _SPECS[name][1], "value": 0.0,
                "unit": _SPECS[name][2], "vs_baseline": 0.0,
                "platform": platform, "fallback": fallback,
                "peak_device_bytes": _peak_device_bytes(),
                "skipped": "time budget",
            })
            continue
        metrics.append(_measure(name, platform, fallback))
    out = dict(head)
    out["metrics"] = metrics
    print(json.dumps(out))


if __name__ == "__main__":
    main()
