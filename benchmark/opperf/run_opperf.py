#!/usr/bin/env python
"""Per-operator forward/backward latency harness.

Parity: the reference's ``benchmark/opperf`` (README:10-17) — run every
registered operator with default inputs, report fwd (and bwd where the
op is differentiable) wall time.  Doubles as an op-coverage smoke test:
the input table is the same spec table the numerics sweep uses
(tests/test_op_numerics.py), so every op the sweep covers is benchmarked.

Usage:
    python benchmark/opperf/run_opperf.py [--runs 20] [--ops dot,relu,...]
        [--output results.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, _ROOT)


def run_one(name, spec, runs, mx, nd, autograd):
    inputs = [nd.array(x) for x in spec.inputs]
    fn = getattr(mx.nd, name, None)
    if fn is None:
        from mxnet_tpu.ndarray.register import make_op_func

        fn = make_op_func(name)
    mx.random.seed(0)

    def fwd():
        out = fn(*inputs, **spec.attrs)
        return out if isinstance(out, list) else [out]

    outs = fwd()  # compile
    for o in outs:
        o.wait_to_read()
    t0 = time.perf_counter()
    for _ in range(runs):
        outs = fwd()
    for o in outs:
        o.wait_to_read()
    fwd_ms = (time.perf_counter() - t0) / runs * 1e3

    bwd_ms = None
    if spec.grad:
        for x in inputs:
            x.attach_grad()

        def step():
            with autograd.record():
                out = fn(*inputs, **spec.attrs)
                head = out[0] if isinstance(out, list) else out
                s = head.sum()
            s.backward()
            return head

        step()
        t0 = time.perf_counter()
        for _ in range(runs):
            h = step()
        h.wait_to_read()
        bwd_ms = (time.perf_counter() - t0) / runs * 1e3 - fwd_ms
    return {"fwd_ms": round(fwd_ms, 4),
            "fwd_bwd_extra_ms": None if bwd_ms is None
            else round(max(bwd_ms, 0.0), 4)}


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--runs", type=int, default=20)
    ap.add_argument("--ops", default=None,
                    help="comma-separated subset (default: all)")
    ap.add_argument("--output", default=None)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd
    from tests.test_op_numerics import _all_specs

    only = set(args.ops.split(",")) if args.ops else None
    results = {}
    for label, name, spec in _all_specs():
        if only is not None and name not in only:
            continue
        try:
            results[label] = run_one(name, spec, args.runs, mx, nd,
                                     autograd)
        except Exception as e:  # a failing op should not kill the sweep
            results[label] = {"error": str(e)[:120]}
    ok = {k: v for k, v in results.items() if "error" not in v}
    errs = {k: v for k, v in results.items() if "error" in v}
    for k in sorted(ok, key=lambda k: -ok[k]["fwd_ms"]):
        v = ok[k]
        extra = ("  +bwd %.3fms" % v["fwd_bwd_extra_ms"]
                 if v["fwd_bwd_extra_ms"] is not None else "")
        print("%-40s fwd %.3fms%s" % (k, v["fwd_ms"], extra))
    if errs:
        print("\nerrors (%d):" % len(errs))
        for k, v in sorted(errs.items()):
            print("  %-38s %s" % (k, v["error"]))
    if args.output:
        with open(args.output, "w") as f:
            json.dump(results, f, indent=1)
        print("\nwrote %s" % args.output)


if __name__ == "__main__":
    main()
