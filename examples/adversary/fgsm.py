"""Fast Gradient Sign Method adversarial examples (reference:
example/adversary/adversary_generation.ipynb).

Exercises input-gradient autograd: ``x.attach_grad()`` + backward through
a trained classifier gives d(loss)/d(input); one FGSM step flips most
predictions while staying imperceptibly close in L-inf.

Usage:
    python examples/adversary/fgsm.py [--epsilon 0.15]
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


def make_data(rs, n):
    """Two-class 8x8 images: class = which diagonal the bar follows."""
    x = rs.randn(n, 1, 8, 8).astype(np.float32) * 0.25
    y = rs.randint(0, 2, n).astype(np.float32)
    for i in range(n):
        idx = np.arange(8)
        if y[i] == 0:
            x[i, 0, idx, idx] += 0.6
        else:
            x[i, 0, idx, 7 - idx] += 0.6
    return x, y


def train_classifier(rs, epochs=12):
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, activation="relu"),
            nn.Flatten(), nn.Dense(2))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 5e-3})
    for _ in range(epochs):
        x, y = make_data(rs, 64)
        with autograd.record():
            loss = loss_fn(net(nd.array(x)), nd.array(y)).mean()
        loss.backward()
        tr.step(64)
    return net, loss_fn


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epsilon", type=float, default=0.3)
    args = ap.parse_args()

    mx.random.seed(0)
    rs = np.random.RandomState(0)
    net, loss_fn = train_classifier(rs)

    xt, yt = make_data(rs, 128)
    x = nd.array(xt)
    y = nd.array(yt)
    clean_acc = float((net(x).argmax(-1) == y).mean().asscalar())

    # FGSM: one signed-gradient step ON THE INPUT
    x.attach_grad()
    with autograd.record():
        loss = loss_fn(net(x), y).mean()
    loss.backward()
    x_adv = x + args.epsilon * x.grad.sign()
    adv_acc = float((net(x_adv).argmax(-1) == y).mean().asscalar())

    linf = float(nd.abs(x_adv - x).max().asscalar())
    print("clean accuracy:       %.3f" % clean_acc)
    print("adversarial accuracy: %.3f (eps=%.3f, L-inf=%.3f)"
          % (adv_acc, args.epsilon, linf))
    assert clean_acc > 0.9 and adv_acc < clean_acc - 0.2, \
        "FGSM should measurably degrade a trained classifier"
    return 0


if __name__ == "__main__":
    sys.exit(main())
