"""Multi-task training: one trunk, two heads, two losses (reference:
example/multi-task/example_multi_task.py — digit class + odd/even).

Exercises joint optimization of heterogeneous objectives through a shared
representation: a softmax classification head and a sigmoid binary head,
each with its own loss, summed into one backward pass and one Trainer.

Task: 12x12 synthetic glyphs; task A = which of 4 shapes, task B = whether
the shape is filled.

Usage:
    python examples/multi-task/train_multitask.py [--epochs 10]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn

S = 12


def make_data(rs, n):
    x = rs.randn(n, 1, S, S).astype(np.float32) * 0.15
    shape_id = rs.randint(0, 4, n)
    filled = rs.randint(0, 2, n)
    for i in range(n):
        a, b = 2, S - 2
        if shape_id[i] == 0:      # square
            x[i, 0, a:b, a] += 1; x[i, 0, a:b, b] += 1
            x[i, 0, a, a:b] += 1; x[i, 0, b, a:b + 1] += 1
        elif shape_id[i] == 1:    # X
            idx = np.arange(a, b)
            x[i, 0, idx, idx] += 1; x[i, 0, idx, S - 1 - idx] += 1
        elif shape_id[i] == 2:    # horizontal bars
            x[i, 0, a::3, a:b] += 1
        else:                     # vertical bars
            x[i, 0, a:b, a::3] += 1
        if filled[i]:
            x[i, 0, 4:S - 4, 4:S - 4] += 0.7
    return x, shape_id.astype(np.float32), filled.astype(np.float32)


class MultiTaskNet(gluon.HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.trunk = nn.HybridSequential()
            self.trunk.add(nn.Conv2D(16, 3, padding=1, activation="relu"),
                           nn.MaxPool2D(2),
                           nn.Conv2D(32, 3, padding=1, activation="relu"),
                           nn.GlobalAvgPool2D(), nn.Flatten())
            self.head_shape = nn.Dense(4)
            self.head_filled = nn.Dense(1)

    def hybrid_forward(self, F, x):
        h = self.trunk(x)
        return self.head_shape(h), self.head_filled(h)


def train(args):
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    net = MultiTaskNet()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 3e-3})

    t0 = time.perf_counter()
    for epoch in range(args.epochs):
        tot = 0.0  # device scalar after first add; pulled once per epoch
        for _ in range(args.iters):
            x, ys, yf = make_data(rs, args.batch)
            with autograd.record():
                ls_logits, lf_logits = net(nd.array(x))
                loss = (ce(ls_logits, nd.array(ys)).mean()
                        + bce(lf_logits.reshape((-1,)),
                              nd.array(yf)).mean())
            loss.backward()
            tr.step(args.batch)
            tot = loss + tot  # device-side accumulate, no per-batch sync
        if epoch % 3 == 0 or epoch == args.epochs - 1:
            # one intentional pull per logged epoch  # mxlint: allow-host-sync
            print("epoch %2d  joint loss %.4f" % (epoch, float(tot.asscalar()) / args.iters))
    print("trained in %.1fs" % (time.perf_counter() - t0))

    x, ys, yf = make_data(rs, 256)
    s_logits, f_logits = net(nd.array(x))
    acc_s = float((s_logits.asnumpy().argmax(-1) == ys).mean())
    acc_f = float(((f_logits.asnumpy().reshape(-1) > 0) == yf).mean())
    print("shape accuracy %.3f, filled accuracy %.3f" % (acc_s, acc_f))
    return acc_s, acc_f


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--batch", type=int, default=64)
    args = ap.parse_args()
    train(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
