#!/usr/bin/env python
"""BERT masked-LM pretraining on synthetic text.

Demonstrates the transformer family end to end: BERTModel (flash-
attention encoders, tied MLM head), AMP bf16, and the device-side
training loop (`JitTrainStep.step_n`) that runs whole windows of
fwd+bwd+Adam as one XLA executable.

    python examples/bert/pretrain_mlm.py [--tpu] [--steps 100]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, parallel  # noqa: E402
from mxnet_tpu.gluon.model_zoo import bert  # noqa: E402

VOCAB = 1000
MASK_ID = 3


def synthetic_batch(batch, seqlen, rs):
    """Token sequences with a learnable rule: every masked position's
    target is (previous token + 1) mod VOCAB."""
    toks = rs.randint(8, VOCAB, (batch, seqlen)).astype(np.int32)
    labels = np.zeros((batch, seqlen), np.float32)
    masked = toks.copy()
    for b in range(batch):
        pos = rs.choice(np.arange(1, seqlen), seqlen // 6, replace=False)
        labels[b, pos] = (toks[b, pos - 1] + 1) % VOCAB
        masked[b, pos] = MASK_ID
    return masked, labels.reshape(-1)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--tpu", action="store_true")
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seqlen", type=int, default=32)
    ap.add_argument("--window", type=int, default=10,
                    help="steps per device-side loop dispatch")
    args = ap.parse_args()

    mx.random.seed(0)
    net = bert.bert_small(vocab_size=VOCAB)
    net.initialize(mx.init.Xavier())
    if args.tpu:
        from mxnet_tpu import amp

        amp.init("bfloat16")
        amp.convert_hybrid_block(net)

    class MLM(gluon.HybridBlock):
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def hybrid_forward(self, F, toks):
            _, _, logits = self.inner(toks)
            return F.reshape(logits, shape=(-1, VOCAB))

    step = parallel.JitTrainStep(
        MLM(net), gluon.loss.SoftmaxCrossEntropyLoss(),
        "adam", {"learning_rate": 3e-3})

    rs = np.random.RandomState(0)
    toks, labels = synthetic_batch(args.batch, args.seqlen, rs)
    t0 = time.time()
    for start in range(0, args.steps, args.window):
        n = min(args.window, args.steps - start)
        loss = step.step_n(n, toks, labels)
        print("step %4d  loss %.4f" % (start + n, float(loss)))
    dt = time.time() - t0
    print("trained %d steps in %.1fs (%.1f samples/s)"
          % (args.steps, dt, args.steps * args.batch / dt))
    assert float(loss) < 2.0, "MLM failed to learn the synthetic rule"


if __name__ == "__main__":
    main()
