#!/usr/bin/env python
"""INT8 quantization: calibrate a model-zoo net, compare fp32 vs int8.

Parity with the reference's ``example/quantization`` (imagenet_gen_qsym
+ imagenet_inference: quantize a model-zoo CNN with naive/entropy
calibration, then measure accuracy drop and speed).  Offline-friendly:
a ResNet-18 (CIFAR geometry) on a synthetic 10-class dataset the model
first fits briefly, so the accuracy comparison is meaningful.

    python examples/quantization/quantize_model.py [--calib entropy]

On TPU the quantized layers run int8×int8→int32 on the MXU
(``ops/quantized_ops.py``); on CPU they exercise the identical graph.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
from examples import _device_setup  # noqa: E402

_device_setup.ensure_devices(1)

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon, nd  # noqa: E402
from mxnet_tpu.contrib import quantization as quant  # noqa: E402
from mxnet_tpu.gluon.model_zoo import vision  # noqa: E402


def make_data(n, rs):
    """Linearly-separable-ish blobs rendered as 3x32x32 images."""
    y = rs.randint(0, 10, n)
    x = rs.randn(n, 3, 32, 32).astype(np.float32) * 0.5
    for i in range(n):
        c = y[i]
        x[i, c % 3, (c * 3) % 28:(c * 3) % 28 + 4, :] += 2.0
    return x, y.astype(np.float32)


def accuracy(net, x, y, batch=64):
    correct = 0
    for i in range(0, len(x), batch):
        # eval-time pull, intentionally per batch  # mxlint: allow-host-sync
        out = net(nd.array(x[i:i + batch])).asnumpy()
        correct += int((out.argmax(1) == y[i:i + batch]).sum())
    return correct / len(x)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--calib", choices=["naive", "entropy"],
                    default="naive")
    ap.add_argument("--train-steps", type=int, default=60)
    ap.add_argument("--n", type=int, default=512)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    x, y = make_data(args.n, rs)
    x_test, y_test = make_data(256, np.random.RandomState(1))

    mx.random.seed(0)
    net = vision.get_resnet(1, 18, thumbnail=True, classes=10)
    net.initialize(mx.init.Xavier())
    net.hybridize()  # whole-graph executable: the fast path on any backend
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    bs = 64
    print("fitting fp32 model (%d steps)..." % args.train_steps,
          flush=True)
    for step in range(args.train_steps):
        i = (step * bs) % (args.n - bs)
        xb, yb = nd.array(x[i:i + bs]), nd.array(y[i:i + bs])
        with autograd.record():
            loss = loss_fn(net(xb), yb).mean()
        loss.backward()
        trainer.step(bs)
        if step % 20 == 0:
            # pull only on logged steps  # mxlint: allow-host-sync
            print("  step %d loss %.3f" % (step, float(loss.asnumpy())),
                  flush=True)
    fp32_acc = accuracy(net, x_test, y_test)

    t0 = time.time()
    out_fp32 = net(nd.array(x_test[:64])).asnumpy()
    fp32_ms = (time.time() - t0) * 1000

    print("calibrating (%s) + quantizing to int8..." % args.calib)
    calib = [nd.array(x[i:i + bs]) for i in range(0, 256, bs)]
    quant.quantize_net_v2(net, quantized_dtype="int8",
                          calib_mode=args.calib, calib_data=calib)
    n_q = sum(isinstance(b, (quant.QuantizedDense, quant.QuantizedConv2D))
              for b in _walk(net))
    int8_acc = accuracy(net, x_test, y_test)
    t0 = time.time()
    out_int8 = net(nd.array(x_test[:64])).asnumpy()
    int8_ms = (time.time() - t0) * 1000

    agree = float((out_fp32.argmax(1) == out_int8.argmax(1)).mean())
    print("quantized layers : %d" % n_q)
    print("fp32 accuracy    : %.3f  (%.0f ms/64-batch)"
          % (fp32_acc, fp32_ms))
    print("int8 accuracy    : %.3f  (%.0f ms/64-batch)"
          % (int8_acc, int8_ms))
    print("top-1 agreement  : %.3f" % agree)
    assert n_q > 0, "nothing was quantized"
    assert int8_acc >= fp32_acc - 0.05, \
        "int8 accuracy dropped more than 5 points"


def _walk(block):
    out = []
    stack = [block]
    while stack:
        b = stack.pop()
        out.append(b)
        stack.extend(b._children.values())
    return out


if __name__ == "__main__":
    main()
