"""Matrix-factorization recommender with sparse embedding gradients
(reference: example/recommenders/demo1-MF.ipynb).

Exercises the sparse tier end to end: ``nn.Embedding(sparse_grad=True)``
produces ``row_sparse`` gradients (only the rows a batch touched), the
optimizer applies lazy row-wise updates, and training cost per step stays
proportional to the BATCH, not the embedding table — the property large
recommender tables rely on in the reference.

Synthetic data: a low-rank user x item preference matrix with noise;
the model recovers it to high rating accuracy.

Usage:
    python examples/recommenders/train_mf.py [--epochs 15]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn

N_USERS, N_ITEMS, RANK = 200, 300, 6


class MFNet(gluon.Block):
    def __init__(self, dim=16, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.user = nn.Embedding(N_USERS, dim, sparse_grad=True)
            self.item = nn.Embedding(N_ITEMS, dim, sparse_grad=True)

    def forward(self, users, items):
        return (self.user(users) * self.item(items)).sum(axis=-1)


def make_truth(rs):
    u = rs.randn(N_USERS, RANK).astype(np.float32)
    v = rs.randn(N_ITEMS, RANK).astype(np.float32)
    return (u @ v.T) / np.sqrt(RANK)


def train(args):
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    truth = make_truth(rs)
    net = MFNet()
    net.initialize(mx.init.Normal(0.1))
    loss_fn = gluon.loss.L2Loss()
    trainer = gluon.Trainer(net.collect_params(), "adagrad",
                            {"learning_rate": 1.0})

    t0 = time.perf_counter()
    for epoch in range(args.epochs):
        tot = 0.0  # device scalar after first add; pulled once per epoch
        for _ in range(args.iters):
            users = rs.randint(0, N_USERS, args.batch)
            items = rs.randint(0, N_ITEMS, args.batch)
            ratings = truth[users, items] + 0.05 * rs.randn(args.batch)
            with autograd.record():
                pred = net(nd.array(users.astype(np.float32)),
                           nd.array(items.astype(np.float32)))
                loss = loss_fn(pred, nd.array(
                    ratings.astype(np.float32))).mean()
            loss.backward()
            # row_sparse gradients: only touched rows carry values
            g = net.user.weight.grad()
            assert getattr(g, "stype", "default") == "row_sparse", g
            trainer.step(args.batch)
            tot = loss + tot  # device-side accumulate, no per-batch sync
        if epoch % 5 == 0 or epoch == args.epochs - 1:
            # one intentional pull per logged epoch  # mxlint: allow-host-sync
            print("epoch %2d  mse %.4f" % (epoch, float(tot.asscalar()) / args.iters))
    print("trained in %.1fs" % (time.perf_counter() - t0))

    users = rs.randint(0, N_USERS, 2048)
    items = rs.randint(0, N_ITEMS, 2048)
    pred = net(nd.array(users.astype(np.float32)),
               nd.array(items.astype(np.float32))).asnumpy()
    rmse = float(np.sqrt(np.mean((pred - truth[users, items]) ** 2)))
    print("held-out RMSE vs truth: %.4f (truth std %.3f)"
          % (rmse, truth.std()))
    return rmse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=15)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--batch", type=int, default=256)
    args = ap.parse_args()
    train(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
