#!/usr/bin/env python
"""Sparse training: wide embedding with row_sparse gradients.

Parity with the reference's example/sparse — a linear model over a huge
sparse feature space where each batch touches a handful of embedding
rows.  With ``sparse_grad=True`` the gradient is a RowSparseNDArray of
just the touched rows and the optimizer applies a lazy gather→update→
scatter, so step cost scales with the batch, not the table.

    python examples/sparse/sparse_embedding.py [--vocab 2000]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon, nd  # noqa: E402
from mxnet_tpu.gluon.contrib.nn import SparseEmbedding  # noqa: E402


def main():  # noqa: C901
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--vocab", type=int, default=2000)
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--steps", type=int, default=150)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    # each example = 8 random feature ids; label from a hidden weight
    hidden = rs.randn(args.vocab).astype(np.float32) * 0.3

    def batch(n=64):
        ids = rs.randint(0, args.vocab, (n, 8)).astype(np.int32)
        y = (hidden[ids].sum(1) > 0).astype(np.float32)
        return nd.array(ids), nd.array(y)

    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(SparseEmbedding(args.vocab, args.dim))
    net.add(gluon.nn.Flatten(), gluon.nn.Dense(1))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.05})
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    t0 = time.time()
    for i in range(args.steps):
        ids, y = batch()
        with autograd.record():
            out = net(ids).reshape((-1,))
            loss = loss_fn(out, y)
        loss.backward()
        g = net[0].weight.grad()
        trainer.step(ids.shape[0])
        if i % 10 == 0:
            # pull only on logged steps
            cur = float(loss.mean().asnumpy())  # mxlint: allow-host-sync
            print("step %3d  loss %.4f  grad rows %d / %d"
                  % (i, cur, g.indices.shape[0], args.vocab))
    print("done in %.1fs" % (time.time() - t0))
    assert float(loss.mean().asnumpy()) < 0.55


if __name__ == "__main__":
    main()
