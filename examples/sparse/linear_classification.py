#!/usr/bin/env python
"""Sparse linear classification from a real LibSVM file.

Parity with the reference's ``example/sparse/linear_classification``
(train.py: LibSVMIter over a libsvm file + ``sparse.dot(csr, weight)``
linear model).  The committed fixture ``data/train.libsvm`` stands in
for the criteo download (zero-egress environment); point ``--data`` at
any libsvm file to train on real data.

The training loop is *structurally sparse* end to end:

* batches arrive as ``CSRNDArray`` straight from ``LibSVMIter`` —
  nothing densifies the (batch, D) design matrix;
* forward is ``sparse.dot(csr, w)`` (gather + scatter-add on the
  stored nonzeros);
* the weight gradient is ``sparse.dot(csr, err, transpose_a=True)`` —
  cost scales with nnz, exactly the reference's kernel shape.

    python examples/sparse/linear_classification.py [--epochs 30]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd  # noqa: E402
from mxnet_tpu.io import LibSVMIter  # noqa: E402
from mxnet_tpu.ndarray import sparse  # noqa: E402

_HERE = os.path.dirname(os.path.abspath(__file__))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data",
                    default=os.path.join(_HERE, "data", "train.libsvm"))
    ap.add_argument("--dim", type=int, default=50,
                    help="feature-space width of the libsvm file")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=30)
    ap.add_argument("--lr", type=float, default=0.5)
    args = ap.parse_args()

    it = LibSVMIter(data_libsvm=args.data, data_shape=(args.dim,),
                    batch_size=args.batch_size)
    print("loaded %s: %d examples, %d features"
          % (args.data, it.num_examples, args.dim))

    mx.random.seed(0)
    w = nd.zeros((args.dim, 1))
    b = 0.0

    t0 = time.time()
    for epoch in range(args.epochs):
        it.reset()
        total, correct, loss_sum, nb = 0, 0, 0.0, 0
        for batch in it:
            x = batch.data[0]            # CSRNDArray — never densified
            y = batch.label[0].asnumpy()
            z = sparse.dot(x, w).asnumpy().reshape(-1) + b
            p = 1.0 / (1.0 + np.exp(-z))
            err = (p - y).astype(np.float32)
            # logistic loss + accuracy on the un-padded rows
            keep = len(y) - batch.pad
            eps = 1e-7
            loss_sum += -np.mean(
                y[:keep] * np.log(p[:keep] + eps)
                + (1 - y[:keep]) * np.log(1 - p[:keep] + eps))
            correct += int(((p[:keep] > 0.5) == y[:keep]).sum())
            total += keep
            nb += 1
            # grad = X^T err / B  — transpose_a sparse dot: scatter-add
            # into the weight rows each nonzero touches
            gw = sparse.dot(x, nd.array(err.reshape(-1, 1)),
                            transpose_a=True)
            w = nd.array(w.asnumpy()
                         - args.lr * gw.asnumpy() / len(y))
            b -= args.lr * float(err.mean())
        if epoch % 5 == 0 or epoch == args.epochs - 1:
            print("epoch %2d  loss %.4f  acc %.3f"
                  % (epoch, loss_sum / nb, correct / total))
    print("done in %.1fs  final acc %.3f" % (time.time() - t0,
                                             correct / total))
    assert correct / total > 0.9, "sparse linear model failed to fit"


if __name__ == "__main__":
    main()
