"""ONNX interchange example: export a model-zoo net, inspect it,
re-import it, and verify output parity.

Run: python examples/onnx/export_import.py
(reference workflow: python/mxnet/contrib/onnx — mx2onnx + onnx2mx)
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))



from _device_setup import ensure_devices  # noqa: E402

ensure_devices(1)

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym as S  # noqa: E402
from mxnet_tpu.contrib import onnx as mxonnx  # noqa: E402
from mxnet_tpu.gluon.model_zoo import vision  # noqa: E402


def main():
    mx.random.seed(0)
    # ONNX is channel-first interchange: build the net NCHW
    net = vision.get_resnet(1, 18, thumbnail=True, classes=10,
                            layout="NCHW")
    net.initialize(mx.init.Xavier())
    x = mx.nd.array(np.random.RandomState(0)
                    .rand(2, 3, 32, 32).astype(np.float32))
    ref = net(x)

    # gluon -> Symbol (symbolic trace) -> ONNX
    graph = net(S.var("data", shape=(2, 3, 32, 32)))
    params = {k: p.data() for k, p in net.collect_params().items()}
    path = mxonnx.export_model(graph, params,
                               onnx_file_path="/tmp/resnet18.onnx",
                               verbose=True)

    meta = mxonnx.get_model_metadata(path)
    print("inputs :", meta["input_tensor_data"])
    print("outputs:", meta["output_tensor_data"])

    # ONNX -> Symbol + params, evaluated through the executor
    sym2, arg_params, aux_params = mxonnx.import_model(path)
    bindings = {"data": x}
    bindings.update(arg_params)
    bindings.update(aux_params)
    out = sym2.eval_imperative(bindings)[0]
    err = float(np.abs(out.asnumpy() - ref.asnumpy()).max())
    print("round-trip max |Δ| = %.2e" % err)
    assert err < 1e-4


if __name__ == "__main__":
    main()
