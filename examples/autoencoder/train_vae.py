"""Variational autoencoder on synthetic glyphs (reference:
example/autoencoder/ + vae-gan/).

Exercises stochastic training graphs: the reparameterization trick
(``nd.random.normal`` inside an autograd scope — gradients flow through
the sampling), a KL-divergence regularizer written in ndarray ops, and
decoder reconstruction.

Usage:
    python examples/autoencoder/train_vae.py [--epochs 15]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn

S = 12
LATENT = 8


def make_data(rs, n):
    """Glyphs from a 2-factor generative process: bar position x width."""
    x = np.zeros((n, S * S), np.float32)
    for i in range(n):
        pos = rs.randint(0, S - 3)
        width = rs.randint(1, 4)
        img = np.zeros((S, S), np.float32)
        img[:, pos:pos + width] = 1.0
        x[i] = img.ravel()
    return x


class VAE(gluon.Block):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.enc = nn.HybridSequential()
            self.enc.add(nn.Dense(64, activation="relu"))
            self.mu = nn.Dense(LATENT)
            self.logvar = nn.Dense(LATENT)
            self.dec = nn.HybridSequential()
            self.dec.add(nn.Dense(64, activation="relu"),
                         nn.Dense(S * S))

    def forward(self, x):
        h = self.enc(x)
        mu, logvar = self.mu(h), self.logvar(h)
        # reparameterization: z = mu + sigma * eps, eps ~ N(0, 1)
        eps = nd.random.normal(0, 1, shape=mu.shape)
        z = mu + (0.5 * logvar).exp() * eps
        return self.dec(z), mu, logvar


def train(args):
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    net = VAE()
    net.initialize(mx.init.Xavier())
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss(from_sigmoid=False)
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 2e-3})

    t0 = time.perf_counter()
    for epoch in range(args.epochs):
        tot = 0.0  # device scalar after first add; pulled once per epoch
        for _ in range(args.iters):
            x = nd.array(make_data(rs, args.batch))
            with autograd.record():
                logits, mu, logvar = net(x)
                recon = bce(logits, x).sum(axis=-1).mean()
                kl = (-0.5 * (1 + logvar - mu ** 2
                              - logvar.exp())).sum(axis=-1).mean()
                loss = recon + kl
            loss.backward()
            tr.step(args.batch)
            tot = loss + tot  # device-side accumulate, no per-batch sync
        if epoch % 5 == 0 or epoch == args.epochs - 1:
            # one intentional pull per logged epoch  # mxlint: allow-host-sync
            print("epoch %2d  elbo-loss %.3f" % (epoch, float(tot.asscalar()) / args.iters))
    print("trained in %.1fs" % (time.perf_counter() - t0))

    # reconstruction quality: thresholded decode matches input pixels
    x = make_data(rs, 256)
    logits, _, _ = net(nd.array(x))
    rec = (logits.asnumpy() > 0).astype(np.float32)
    pix_acc = float((rec == x).mean())
    print("reconstruction pixel accuracy: %.3f" % pix_acc)
    return pix_acc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=15)
    ap.add_argument("--iters", type=int, default=25)
    ap.add_argument("--batch", type=int, default=64)
    args = ap.parse_args()
    train(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
