"""DCGAN on synthetic 2-D shape images (reference: example/gan/dcgan.py).

Exercises the adversarial-training surface: TWO networks with TWO
independent Trainers updated alternately under one autograd scope each,
Conv2DTranspose generator, BatchNorm+LeakyReLU discriminator, and the
label-flip loss bookkeeping — the training-loop shape every GAN recipe
written against the reference uses.

Synthetic "real" data: 16x16 images of axis-aligned bright squares.  After
a few epochs the generator's samples concentrate energy in a contiguous
blob (scored below); the point is the training mechanics, not FID.

Usage:
    python examples/gan/train_dcgan.py [--epochs 6]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn

LATENT = 16


def real_batch(rs, n, size=16):
    imgs = np.full((n, 1, size, size), -1.0, np.float32)
    for i in range(n):
        w = rs.randint(4, 9)
        x0 = rs.randint(0, size - w)
        y0 = rs.randint(0, size - w)
        imgs[i, 0, y0:y0 + w, x0:x0 + w] = 1.0
    return imgs


def build_generator():
    net = nn.HybridSequential(prefix="gen_")
    with net.name_scope():
        # latent (N, LATENT, 1, 1) -> (N, 1, 16, 16)
        net.add(nn.Conv2DTranspose(64, 4, 1, 0, use_bias=False),  # 4x4
                nn.BatchNorm(), nn.Activation("relu"),
                nn.Conv2DTranspose(32, 4, 2, 1, use_bias=False),  # 8x8
                nn.BatchNorm(), nn.Activation("relu"),
                nn.Conv2DTranspose(1, 4, 2, 1, use_bias=False),   # 16x16
                nn.Activation("tanh"))
    return net


def build_discriminator():
    net = nn.HybridSequential(prefix="disc_")
    with net.name_scope():
        net.add(nn.Conv2D(32, 4, 2, 1, use_bias=False),
                nn.LeakyReLU(0.2),
                nn.Conv2D(64, 4, 2, 1, use_bias=False),
                nn.BatchNorm(), nn.LeakyReLU(0.2),
                nn.Conv2D(1, 4, 1, 0, use_bias=False),
                nn.Flatten())
    return net


def train(args):
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    gen, disc = build_generator(), build_discriminator()
    gen.initialize(mx.init.Normal(0.02))
    disc.initialize(mx.init.Normal(0.02))
    gen.hybridize()
    disc.hybridize()

    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    g_tr = gluon.Trainer(gen.collect_params(), "adam",
                         {"learning_rate": 2e-3, "beta1": 0.5})
    d_tr = gluon.Trainer(disc.collect_params(), "adam",
                         {"learning_rate": 2e-3, "beta1": 0.5})

    bs = args.batch
    ones = nd.ones((bs,))
    zeros = nd.zeros((bs,))
    t0 = time.perf_counter()
    for epoch in range(args.epochs):
        dl = gl = 0.0  # device scalars after first add; pulled once per epoch
        for _ in range(args.iters):
            real = nd.array(real_batch(rs, bs))
            noise = nd.array(rs.randn(bs, LATENT, 1, 1).astype(np.float32))
            # -- discriminator: real->1, fake->0 (fake detached) --------
            with autograd.record():
                out_r = disc(real).reshape((-1,))
                fake = gen(noise)
                out_f = disc(fake.detach()).reshape((-1,))
                errd = (loss_fn(out_r, ones) + loss_fn(out_f, zeros)).mean()
            errd.backward()
            d_tr.step(bs)
            # -- generator: fool the discriminator ----------------------
            with autograd.record():
                out = disc(gen(noise)).reshape((-1,))
                errg = loss_fn(out, ones).mean()
            errg.backward()
            g_tr.step(bs)
            dl = errd + dl  # device-side accumulate, no per-batch sync
            gl = errg + gl
        # two intentional pulls per epoch, at the log point
        d_epoch = float(dl.asscalar()) / args.iters  # mxlint: allow-host-sync
        g_epoch = float(gl.asscalar()) / args.iters  # mxlint: allow-host-sync
        print("epoch %d  D %.4f  G %.4f" % (epoch, d_epoch, g_epoch))
    print("trained in %.1fs" % (time.perf_counter() - t0))

    # structure score: real squares have high spatial autocorrelation —
    # noise scores ~0, learned blobs clearly above
    noise = nd.array(rs.randn(64, LATENT, 1, 1).astype(np.float32))
    samples = gen(noise).asnumpy()[:, 0]
    acorr = np.mean([
        np.corrcoef(s[:, :-1].ravel(), s[:, 1:].ravel())[0, 1]
        for s in samples])
    print("sample spatial autocorrelation: %.3f" % acorr)
    return acorr


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--iters", type=int, default=16)
    ap.add_argument("--batch", type=int, default=32)
    args = ap.parse_args()
    train(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
