"""Shared example helper: pin a deterministic CPU backend.

This image's sitecustomize imports jax (registering the axon/TPU
backend) before shell env vars can influence it, and probing the
ambient backend can HANG when the chip tunnel is unhealthy — so
examples pin CPU via the config API unless the user opts into the
ambient backend with MXNET_EXAMPLE_PLATFORM=ambient.
"""
from __future__ import annotations

import os
import re


def ensure_devices(n_needed=1):
    import jax

    if os.environ.get("MXNET_EXAMPLE_PLATFORM") == "ambient":
        return
    try:
        from jax._src import xla_bridge as _xb

        _xb._clear_backends()
        _xb.get_backend.cache_clear()
    except Exception:
        pass
    n = max(8, n_needed)
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except Exception:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       os.environ.get("XLA_FLAGS", ""))
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=%d" % n
        ).strip()
