#!/usr/bin/env python
"""LeNet image classification, both training APIs.

Parity with the reference's example/image-classification/train_mnist.py,
shown both ways:
  --api gluon    imperative Gluon + Trainer (hybridized)
  --api module   symbolic Module.fit with metric/callback hooks

Runs on synthetic MNIST-shaped data by default (this environment has no
network egress); pass --mnist DIR to use a real MNIST directory.
"""
from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon, nd  # noqa: E402


def synthetic_mnist(n=2048, seed=0):
    """Linearly-separable digit-shaped data so the example converges."""
    rs = np.random.RandomState(seed)
    protos = rs.rand(10, 1, 28, 28).astype(np.float32)
    labels = rs.randint(0, 10, n)
    x = protos[labels] + 0.1 * rs.randn(n, 1, 28, 28).astype(np.float32)
    return x.astype(np.float32), labels.astype(np.float32)


def build_lenet_gluon():
    net = gluon.nn.HybridSequential()
    net.add(
        gluon.nn.Conv2D(20, 5, activation="tanh"),
        gluon.nn.MaxPool2D(2, 2),
        gluon.nn.Conv2D(50, 5, activation="tanh"),
        gluon.nn.MaxPool2D(2, 2),
        gluon.nn.Flatten(),
        gluon.nn.Dense(500, activation="tanh"),
        gluon.nn.Dense(10),
    )
    return net


def train_gluon(x, y, epochs, batch, ctx):
    net = build_lenet_gluon()
    net.initialize(mx.init.Xavier(), ctx=ctx)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()
    for epoch in range(epochs):
        metric.reset()
        for i in range(0, len(x), batch):
            data = nd.array(x[i:i + batch], ctx=ctx)
            label = nd.array(y[i:i + batch], ctx=ctx)
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(data.shape[0])
            metric.update(label, out)
        print("epoch %d: train accuracy %.3f" % (epoch, metric.get()[1]))
    return metric.get()[1]


def train_module(x, y, epochs, batch, ctx):
    data = mx.sym.var("data")
    net = mx.sym.Convolution(data, kernel=(5, 5), num_filter=20)
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2), stride=(2, 2))
    net = mx.sym.Convolution(net, kernel=(5, 5), num_filter=50)
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2), stride=(2, 2))
    net = mx.sym.FullyConnected(mx.sym.flatten(net), num_hidden=500)
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.FullyConnected(net, num_hidden=10)
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    train_iter = mx.io.NDArrayIter(x, y, batch, shuffle=True)
    mod = mx.module.Module(net, context=ctx)
    mod.fit(train_iter, num_epoch=epochs,
            initializer=mx.init.Xavier(),
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
            eval_metric="acc",
            batch_end_callback=mx.callback.Speedometer(batch, 16))
    score = mod.score(mx.io.NDArrayIter(x, y, batch), "acc")
    acc = dict(score)["accuracy"]
    print("module final accuracy %.3f" % acc)
    return acc


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--api", choices=("gluon", "module"), default="gluon")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--tpu", action="store_true",
                    help="place data/params on the TPU context")
    args = ap.parse_args()
    ctx = mx.tpu() if args.tpu else mx.cpu()
    x, y = synthetic_mnist()
    fn = train_gluon if args.api == "gluon" else train_module
    acc = fn(x, y, args.epochs, args.batch, ctx)
    assert acc > 0.9, "example failed to converge"


if __name__ == "__main__":
    main()
