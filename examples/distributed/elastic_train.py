#!/usr/bin/env python
"""Elastic training via mesh-shape-agnostic checkpoints
(docs/fault_tolerance.md "Elastic training").

A fleet resize in the middle of a run is a checkpoint boundary, not a
restart-from-scratch: ``JitTrainStep.save_states`` writes every
parameter and optimizer leaf ONCE in its logical shape (MXGC1 global
format, with its PartitionSpec and a per-entry checksum), so the same
file restores onto any mesh whose axes divide the spec'd dims.  This
example walks the full resize cycle on the forced-CPU mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/distributed/elastic_train.py

1. train at dp=8, checkpoint;
2. "preemption" drops half the fleet — restore the SAME file at dp=4
   and keep training;
3. capacity returns — checkpoint at dp=4, restore at dp=8, finish.

The loss trend is continuous across both resizes because the restored
optimizer state (adam moments, step count) is bitwise the saved one —
only the placement changed.
"""
from __future__ import annotations

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, parallel  # noqa: E402
from mxnet_tpu.sharding import Mesh, P  # noqa: E402

BATCH, DIM = 16, 8
STEPS_PER_PHASE = 5


def make_step(dp):
    """A fresh process-after-resize: new net + step on a dp-way mesh."""
    mx.random.seed(42)
    net = gluon.nn.Dense(DIM, in_units=DIM)
    net.initialize(mx.init.Xavier())
    return parallel.JitTrainStep(
        net, gluon.loss.L2Loss(), "adam", {"learning_rate": 0.05},
        mesh=Mesh({"data": dp}),
        param_rule=lambda name, shape: P("data"))


def train(step, x, y, n):
    losses = [float(step.step(x, y)) for _ in range(n)]
    return losses


def main():
    if len(jax.devices()) < 8:
        print("need 8 devices (set XLA_FLAGS="
              "--xla_force_host_platform_device_count=8); nothing to do")
        return 0

    rs = np.random.RandomState(0)
    x = rs.randn(BATCH, DIM).astype(np.float32)
    y = rs.randn(BATCH, DIM).astype(np.float32)
    ckpt = os.path.join(tempfile.mkdtemp(prefix="elastic_train_"),
                        "elastic.mxgc")

    # phase 1: full fleet
    step8 = make_step(8)
    losses = train(step8, x, y, STEPS_PER_PHASE)
    step8.save_states(ckpt)
    print("dp=8 phase 1: loss %.4f -> %.4f, checkpoint at step %d"
          % (losses[0], losses[-1], step8._t))

    # phase 2: half the fleet was preempted — same file, dp=4 mesh.
    # One warm-up step establishes the dp=4 placement (compiles the
    # step and shards the fresh params); load_states then overwrites
    # every value — weights, adam moments, step count — from the file.
    step4 = make_step(4)
    step4.step(x, y)
    step4.load_states(ckpt)
    assert step4._t == STEPS_PER_PHASE
    losses4 = train(step4, x, y, STEPS_PER_PHASE)
    step4.save_states(ckpt)
    print("dp=4 phase 2: resumed at step %d, loss %.4f -> %.4f"
          % (STEPS_PER_PHASE, losses4[0], losses4[-1]))

    # phase 3: capacity restored — same file again, back to dp=8
    step8b = make_step(8)
    step8b.step(x, y)
    step8b.load_states(ckpt)
    assert step8b._t == 2 * STEPS_PER_PHASE
    losses8 = train(step8b, x, y, STEPS_PER_PHASE)
    print("dp=8 phase 3: resumed at step %d, loss %.4f -> %.4f"
          % (2 * STEPS_PER_PHASE, losses8[0], losses8[-1]))

    # the trend never resets: each phase starts at (or below) the loss
    # the previous phase ended with, because state moved bitwise
    assert losses4[0] <= losses[-1] + 1e-4
    assert losses8[0] <= losses4[-1] + 1e-4
    print("elastic cycle complete: dp=8 -> dp=4 -> dp=8, loss monotone "
          "across both resizes")
    return 0


if __name__ == "__main__":
    sys.exit(main())
