#!/usr/bin/env python
"""Elastic resharding over the GSPMD substrate (docs/sharding.md).

A long-lived training fleet resizes: preemptions shrink it, restored
capacity grows it.  With first-class named sharding the resize is a
*placement change, not a data change* — the parameters keep their
values and move onto the new mesh with one ``reshard`` per resize
event.  This example simulates a shrink (8→4 devices) and a regrow
(4→8) on the forced-CPU mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/distributed/elastic_reshard.py

This is the *live-array* half of elasticity: values already on devices
move to a new mesh in place.  The *checkpoint* half — a dp=8
``save_states`` file restoring onto a dp=4 mesh across a process
boundary — is ``elastic_train.py`` next to this file, and the membership
protocol that decides WHEN to resize is docs/fault_tolerance.md
"Elastic training".

Each event rebuilds the ``Mesh`` from the surviving devices and
reshards every parameter onto it.  The reshard-per-event loop below is
the one legitimate reshard-in-a-loop in the tree (suppressed in
tools/mxlint_suppressions.txt): it runs once per *resize*, not once
per step — resharding per training step is exactly what SH902 exists
to catch.
"""
from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd  # noqa: E402
from mxnet_tpu.sharding import Mesh, P  # noqa: E402


def main():
    devices = jax.devices()
    if len(devices) < 2:
        print("need >=2 devices (set XLA_FLAGS="
              "--xla_force_host_platform_device_count=8); nothing to do")
        return 0

    params = {
        "dense0_weight": nd.array(np.random.randn(64, 32).astype("f4")),
        "dense1_weight": nd.array(np.random.randn(32, 64).astype("f4")),
    }
    checksums = {k: float(v.asnumpy().sum()) for k, v in params.items()}

    n = len(devices)
    # resize schedule: full fleet -> half (preemption) -> full (restore)
    schedule = [devices[:n], devices[:n // 2], devices[:n]]
    for event, alive in enumerate(schedule):
        mesh = Mesh({"data": len(alive)}, devices=alive)
        with mesh:
            for name, p in params.items():
                p.reshard(P("data"), mesh=mesh)
        nd.waitall()  # mxlint: allow-host-sync  (settle once per resize)
        for name, p in params.items():
            assert len(p.sharding.device_set) == len(alive)
            # mxlint: allow-host-sync  (per-event integrity check)
            assert abs(float(p.asnumpy().sum()) - checksums[name]) < 1e-3
        print("resize %d: %d devices, params resharded, values intact"
              % (event, len(alive)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
