#!/usr/bin/env python
"""Multi-process data-parallel training via the distributed KVStore.

Launch (spawns 1 parameter server + N workers on this machine, or run
one role per host with the DMLC_* env set):

    python tools/launch.py -n 2 --kv-store dist_sync \
        python examples/distributed/train_dist.py

Each worker computes gradients on its own shard of the data; the server
sums pushes from all workers per key (barrier-per-key sync) and runs the
optimizer server-side; workers pull the updated weights back.
"""
from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon, nd  # noqa: E402


def main():
    kv = mx.kvstore.create(os.environ.get("MXNET_KVSTORE_MODE",
                                          "dist_sync"))
    rank, nworker = kv.rank, kv.num_workers
    print("worker %d/%d up" % (rank, nworker))

    # every worker sees a disjoint shard of one global dataset
    rs = np.random.RandomState(0)
    x_all = rs.randn(512, 16).astype(np.float32)
    w_true = rs.randn(16, 1).astype(np.float32)
    y_all = (x_all @ w_true).astype(np.float32)
    shard = slice(rank * 512 // nworker, (rank + 1) * 512 // nworker)
    x, y = nd.array(x_all[shard]), nd.array(y_all[shard])

    mx.random.seed(0)  # identical init on every worker
    net = gluon.nn.Dense(1)
    net.initialize(mx.init.Xavier())
    net(x[:1])
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=kv)
    loss_fn = gluon.loss.L2Loss()
    for i in range(25):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(x.shape[0] * nworker)  # global batch size
        if rank == 0 and i % 5 == 0:
            # pull only on logged steps  # mxlint: allow-host-sync
            print("step %d loss %.5f" % (i, float(loss.mean().asnumpy())))

    final = float(loss.mean().asnumpy())
    print("worker %d final loss %.6f" % (rank, final))
    assert final < 0.05, "distributed training failed to converge"
    kv.barrier()
    if rank == 0:
        kv.stop()


if __name__ == "__main__":
    main()
