"""Multi-host data-parallel training as ONE logical XLA program.

The GSPMD alternative to the parameter-server tier in ``train_dist.py``:
every process joins ``jax.distributed`` (reference analogue: the NCCL
allreduce tier), the global mesh spans all hosts' devices, each process
feeds its own host-local data shard, and the compiled step's gradient
reduction rides ICI within a host / DCN across hosts with no server
round trip.

Launch (2 "hosts" simulated locally; on a pod use --launcher ssh):
    python tools/launch.py -n 2 --backend gspmd \
        python examples/distributed/train_gspmd_multihost.py
"""
import os
import sys

if __name__ == "__main__" and os.environ.get("DMLC_NUM_WORKER") is None:
    print(__doc__)
    sys.exit("run via tools/launch.py --backend gspmd (needs DMLC_* env)")

# virtual CPU devices when no real accelerator topology is present
if "XLA_FLAGS" not in os.environ:
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import parallel


def main():
    nproc, rank = parallel.init_multihost()
    mesh = parallel.global_mesh()
    if rank == 0:
        print("mesh over %d devices, %d processes"
              % (mesh.devices.size, nproc))

    # shared model (same seed everywhere), per-process data shard
    rs_shared = np.random.RandomState(0)
    w_true = rs_shared.randn(8, 1).astype(np.float32)
    rs = np.random.RandomState(100 + rank)
    x_local = rs.randn(64, 8).astype(np.float32)
    y_local = x_local @ w_true + 0.01 * rs.randn(64, 1).astype(np.float32)

    xg = parallel.host_local_to_global(x_local, mesh, P("data"))
    yg = parallel.host_local_to_global(y_local, mesh, P("data"))

    w = jnp.zeros((8, 1), jnp.float32)

    @jax.jit
    def step(w, x, y):
        loss, g = jax.value_and_grad(
            lambda w: jnp.mean((x @ w - y) ** 2))(w)
        return w - 0.1 * g, loss

    for i in range(100):
        w, loss = step(w, xg, yg)
        if rank == 0 and i % 20 == 0:
            print("step %3d  loss %.6f" % (i, float(loss)))
    parallel.sync_global_devices("done")
    err = float(np.abs(np.asarray(w) - w_true).max())
    print("rank %d final |w - w_true| = %.4f" % (rank, err))
    return 0


if __name__ == "__main__":
    sys.exit(main())
