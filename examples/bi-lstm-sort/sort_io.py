"""Bidirectional LSTM learns to sort short digit sequences (reference:
example/bi-lstm-sort/).

The classic seq2seq-lite demo: input is a sequence of digits, target is
the same digits sorted; a bidirectional LSTM sees the whole sequence both
ways and emits the sorted sequence position-wise.  Exercises the
``bidirectional=True`` fused RNN layer and position-wise classification.

Usage:
    python examples/bi-lstm-sort/sort_io.py [--epochs 15]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn, rnn

VOCAB = 10
SEQ = 6


def batch(rs, n):
    x = rs.randint(0, VOCAB, (n, SEQ))
    y = np.sort(x, axis=1)
    return x.astype(np.float32), y.astype(np.float32)


class SortNet(gluon.Block):
    def __init__(self, hidden=64, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.embed = nn.Embedding(VOCAB, 32)
            self.lstm = rnn.LSTM(hidden, num_layers=1, layout="NTC",
                                 bidirectional=True)
            self.proj = nn.Dense(VOCAB, flatten=False)

    def forward(self, x):
        return self.proj(self.lstm(self.embed(x)))  # (N, T, VOCAB)


def train(args):
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    net = SortNet()
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 3e-3})

    t0 = time.perf_counter()
    for epoch in range(args.epochs):
        tot = 0.0  # device scalar after first add; pulled once per epoch
        for _ in range(args.iters):
            x, y = batch(rs, args.batch)
            with autograd.record():
                logits = net(nd.array(x))
                loss = loss_fn(logits.reshape((-3, 0)),
                               nd.array(y.reshape(-1))).mean()
            loss.backward()
            tr.step(args.batch)
            tot = loss + tot  # device-side accumulate, no per-batch sync
        if epoch % 5 == 0 or epoch == args.epochs - 1:
            # one intentional pull per logged epoch  # mxlint: allow-host-sync
            print("epoch %2d  loss %.4f" % (epoch, float(tot.asscalar()) / args.iters))
    print("trained in %.1fs" % (time.perf_counter() - t0))

    x, y = batch(rs, 256)
    pred = net(nd.array(x)).asnumpy().argmax(-1)
    elem_acc = float((pred == y).mean())
    seq_acc = float((pred == y).all(axis=1).mean())
    print("element accuracy %.3f, full-sequence accuracy %.3f"
          % (elem_acc, seq_acc))
    return elem_acc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=15)
    ap.add_argument("--iters", type=int, default=25)
    ap.add_argument("--batch", type=int, default=64)
    args = ap.parse_args()
    train(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
