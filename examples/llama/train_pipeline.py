"""Pipeline-parallel Llama training example (non-identical stages).

Partitions a Llama stack into pipeline stages — embedding fused into
stage 0, final norm + LM head into the last — places each stage's
weights on its own device, and trains with the host-scheduled GPipe
schedule (microbatches overlap via async dispatch; backward recomputes
each stage's forward).

Run on the 8-virtual-device CPU mesh:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/llama/train_pipeline.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))



from _device_setup import ensure_devices  # noqa: E402

ensure_devices(4)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import parallel  # noqa: E402
from mxnet_tpu.gluon.model_zoo import llama  # noqa: E402

VOCAB = 1024
PP = 4


def cross_entropy(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[..., None], -1))


def main():
    if len(jax.devices()) < PP:
        print("need %d devices (see module docstring)" % PP)
        return
    mx.random.seed(0)
    net = llama.LlamaModel(VOCAB, units=128, hidden_size=256,
                           num_layers=PP, num_heads=4, num_kv_heads=2)
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(np.zeros((1, 32), np.int32)))  # resolve shapes

    fns, params, refs, shared = parallel.partition_llama(net, PP)
    pipe = parallel.HostPipeline(fns, params, cross_entropy,
                                 shared_params=shared)
    print("stages:", [len(p) for p in params], "params each; devices:",
          [str(d) for d in pipe.devices])

    rs = np.random.RandomState(0)
    for step in range(5):
        toks = rs.randint(0, VOCAB, (8, 32)).astype(np.int32)
        labels = np.roll(toks, -1, axis=1).astype(np.int32)
        x_mbs = [toks[i::4] for i in range(4)]    # 4 microbatches
        y_mbs = [labels[i::4] for i in range(4)]
        loss = pipe.sgd_step(x_mbs, y_mbs, lr=0.1)
        print("step %d: loss %.4f" % (step, loss))

    # sync updated weights back into the gluon net
    for prefs, ps in zip(refs, pipe.params):
        for p, a in zip(prefs, ps):
            p.set_data(mx.nd.NDArray(a))
    print("weights synced back to the gluon model")


if __name__ == "__main__":
    main()
