"""Train a small Llama-architecture decoder LM on synthetic data.

Shows the TPU-first decoder stack: RoPE + GQA + SwiGLU + RMSNorm with
Pallas flash attention, the whole train step compiled as one executable
(JitTrainStep), and optional sequence-parallel ring attention over an
``sp`` mesh axis for long sequences (``--ring`` — the SURVEY §5.7
long-context design; on one host it runs over virtual devices, on a pod
the same code rides the ICI ring).

Usage:
    python examples/llama/train_lm.py [--steps 30] [--ring]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from mxnet_tpu.gluon.model_zoo import llama


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seqlen", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--ring", action="store_true",
                    help="sequence-parallel ring attention over an "
                         "8-way sp mesh")
    args = ap.parse_args()

    mx.random.seed(0)
    net = llama.LlamaModel(args.vocab, units=128, hidden_size=256,
                           num_layers=4, num_heads=8, num_kv_heads=4)
    net.initialize(mx.init.Xavier())
    if args.ring:
        mesh = parallel.make_mesh({"sp": 8})
        net.sequence_parallel(mesh)
        print("ring attention over mesh", dict(mesh.shape))

    vocab = args.vocab

    class LM(gluon.HybridBlock):
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def hybrid_forward(self, F, toks):
            return F.reshape(self.inner(toks), shape=(-1, vocab))

    step = parallel.JitTrainStep(
        LM(net), gluon.loss.SoftmaxCrossEntropyLoss(),
        "adamw", {"learning_rate": 3e-4})

    rng = np.random.RandomState(0)
    # synthetic "language": next token = (token * 31 + 7) % vocab, so the
    # model has a learnable structure and loss should fall fast
    start = rng.randint(0, args.vocab, (args.batch, 1))
    seq = [start]
    for _ in range(args.seqlen):
        seq.append((seq[-1] * 31 + 7) % args.vocab)
    toks = np.concatenate(seq[:-1], axis=1).astype(np.int32)
    labels = np.concatenate(seq[1:], axis=1).reshape(-1).astype(np.float32)

    t0 = time.perf_counter()
    for i in range(args.steps):
        loss = step.step(toks, labels)
        if i % 10 == 0 or i == args.steps - 1:
            print("step %3d  loss %.4f" % (i, float(loss)))
    dt = time.perf_counter() - t0
    tok_s = args.batch * args.seqlen * args.steps / dt
    print("done: %.0f tokens/s (incl. compile)" % tok_s)
    return 0


if __name__ == "__main__":
    sys.exit(main())
