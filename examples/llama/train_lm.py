"""Train a small Llama-architecture decoder LM on synthetic data.

Shows the TPU-first decoder stack: RoPE + GQA + SwiGLU + RMSNorm with
Pallas flash attention, the whole train step compiled as one executable
(JitTrainStep), and optional sequence-parallel ring attention over an
``sp`` mesh axis for long sequences (``--ring`` — the SURVEY §5.7
long-context design; on one host it runs over virtual devices, on a pod
the same code rides the ICI ring).

Usage:
    python examples/llama/train_lm.py [--steps 30] [--ring]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

if "--ring" in sys.argv:
    # the ring demo needs an 8-way mesh.  A real multi-chip backend (a
    # pod host) is used as-is — the ring rides the ICI; otherwise build
    # the mesh from 8 virtual CPU devices (the same fallback the test
    # suite and the multichip dryrun use)
    import jax

    try:
        n_real = len(jax.devices())
    except Exception:
        n_real = 0
    if n_real < 8:
        try:
            from jax._src import xla_bridge as _xb

            _xb._clear_backends()
            _xb.get_backend.cache_clear()
        except Exception:
            pass
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", 8)
        except Exception:
            pass

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from mxnet_tpu.gluon.model_zoo import llama


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seqlen", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--ring", action="store_true",
                    help="sequence-parallel ring attention over an "
                         "8-way sp mesh")
    ap.add_argument("--clip", type=float, default=1.0,
                    help="global-norm gradient clip (the standard LM "
                         "training guard; <=0 disables)")
    args = ap.parse_args()

    mx.random.seed(0)
    if args.ring:
        # virtual-CPU ring steps re-trace shard_map per layer per
        # backward (minutes each at full size — a CPU-emulation cost,
        # not a TPU one), so the demo config stays small
        args.steps = min(args.steps, 3)
        args.seqlen = min(args.seqlen, 64)
        net = llama.LlamaModel(args.vocab, units=64, hidden_size=128,
                               num_layers=1, num_heads=4, num_kv_heads=2)
    else:
        net = llama.LlamaModel(args.vocab, units=128, hidden_size=256,
                               num_layers=4, num_heads=8, num_kv_heads=4)
    net.initialize(mx.init.Xavier())
    if args.ring:
        mesh = parallel.make_mesh({"sp": 8})
        net.sequence_parallel(mesh)
        print("ring attention over mesh", dict(mesh.shape))

    vocab = args.vocab

    class LM(gluon.HybridBlock):
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def hybrid_forward(self, F, toks):
            return F.reshape(self.inner(toks), shape=(-1, vocab))

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    if args.ring:
        # ring mode drives the mesh collectives itself (scatter -> ring
        # -> gather per layer), so train eagerly; the flash path compiles
        # the whole step into one executable instead
        trainer = gluon.Trainer(net.collect_params(), "adamw",
                                {"learning_rate": 3e-4})
        step = None
    else:
        step = parallel.JitTrainStep(
            LM(net), loss_fn, "adamw", {"learning_rate": 3e-4},
            clip_global_norm=args.clip if args.clip > 0 else None)

    rng = np.random.RandomState(0)
    # synthetic "language": next token = (token * 31 + 7) % vocab, so the
    # model has a learnable structure and loss should fall fast
    start = rng.randint(0, args.vocab, (args.batch, 1))
    seq = [start]
    for _ in range(args.seqlen):
        seq.append((seq[-1] * 31 + 7) % args.vocab)
    toks = np.concatenate(seq[:-1], axis=1).astype(np.int32)
    labels = np.concatenate(seq[1:], axis=1).reshape(-1).astype(np.float32)

    t0 = time.perf_counter()
    for i in range(args.steps):
        if step is not None:
            loss = step.step(toks, labels)
            val = float(loss)
        else:
            from mxnet_tpu import autograd, nd

            with autograd.record():
                logits = net(nd.array(toks.astype(np.float32)))
                l = loss_fn(logits.reshape(-3, 0),
                            nd.array(labels)).mean()
            l.backward()
            if args.clip > 0:
                grads = [p.grad() for p in net.collect_params().values()
                         if p.grad_req != "null"]
                gluon.utils.clip_global_norm(grads, args.clip)
            trainer.step(1)
        if i % 10 == 0 or i == args.steps - 1:
            # pull only on logged steps  # mxlint: allow-host-sync
            print("step %3d  loss %.4f" % (i, float(l.asscalar())))
    dt = time.perf_counter() - t0
    tok_s = args.batch * args.seqlen * args.steps / dt
    print("done: %.0f tokens/s (incl. compile)" % tok_s)
    return 0


if __name__ == "__main__":
    sys.exit(main())
