#!/usr/bin/env python
"""Manual model parallelism with ``ctx_group`` / ``group2ctx``.

Parity with the reference's ``example/model-parallel/
matrix_factorization/train.py:78-84``: the wide embedding tables live
in one context group ("embed", device 0 — where the memory is) while
the interaction/output layers live in another ("dense", device 1), and
``simple_bind(group2ctx=...)`` places each graph node on its group's
device.  On TPU the groups map to different chips and XLA inserts the
boundary transfers.

    python examples/model_parallel/matrix_factorization.py
"""
from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
from examples import _device_setup  # noqa: E402

_device_setup.ensure_devices(2)

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym as S  # noqa: E402


def build(num_users, num_items, factor):
    user = S.var("user")
    item = S.var("item")
    score = S.var("score")
    # group "embed": the big tables (reference puts these on the
    # memory-rich device)
    with mx.AttrScope(ctx_group="embed"):
        u = S.Embedding(user, input_dim=num_users, output_dim=factor,
                        name="user_embed")
        v = S.Embedding(item, input_dim=num_items, output_dim=factor,
                        name="item_embed")
    # group "dense": the interaction + readout
    with mx.AttrScope(ctx_group="dense"):
        pred = S.sum(u * v, axis=1)
        loss = S.make_loss(S.mean(S.square(pred - score)))
    return loss


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--users", type=int, default=500)
    ap.add_argument("--items", type=int, default=300)
    ap.add_argument("--factor", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=25)
    # mean-loss gradients are ~1/batch_size per touched row, so
    # the SGD rate is scaled up accordingly
    ap.add_argument("--lr", type=float, default=40.0)
    args = ap.parse_args()

    import jax

    devs = jax.devices()
    group2ctx = {"embed": mx.Context(devs[0].platform, 0),
                 "dense": mx.Context(devs[0].platform,
                                     1 if len(devs) > 1 else 0)}
    print("placement: embed -> %s  dense -> %s"
          % (group2ctx["embed"], group2ctx["dense"]))

    # synthetic low-rank ratings
    rs = np.random.RandomState(0)
    u_true = rs.randn(args.users, args.factor) * 0.5
    v_true = rs.randn(args.items, args.factor) * 0.5
    n = 8192
    uid = rs.randint(0, args.users, n).astype(np.float32)
    iid = rs.randint(0, args.items, n).astype(np.float32)
    score = np.sum(u_true[uid.astype(int)] * v_true[iid.astype(int)],
                   axis=1).astype(np.float32)

    loss_sym = build(args.users, args.items, args.factor)
    bs = 512
    exe = loss_sym.simple_bind(ctx=group2ctx["embed"],
                               group2ctx=group2ctx,
                               user=(bs,), item=(bs,), score=(bs,))
    for name, arr in exe.arg_dict.items():
        if name.endswith("weight"):
            arr._set_data(np.asarray(
                rs.randn(*arr.shape) * 0.1, np.float32))

    t0 = time.time()
    first = last = None
    for epoch in range(args.epochs):
        total = 0.0
        for i in range(0, n, bs):
            exe.arg_dict["user"]._set_data(uid[i:i + bs])
            exe.arg_dict["item"]._set_data(iid[i:i + bs])
            exe.arg_dict["score"]._set_data(score[i:i + bs])
            out = exe.forward(is_train=True)[0]
            exe.backward()
            for name, arr in exe.arg_dict.items():
                g = exe.grad_dict.get(name)
                if g is not None and name.endswith("weight"):
                    arr._set_data(arr.data() - args.lr * g.data())
            total = out + total  # device-side accumulate, no per-batch sync
        # one intentional pull per epoch  # mxlint: allow-host-sync
        mse = float(total.asscalar()) / (n // bs)
        if first is None:
            first = mse
        last = mse
        if epoch % 3 == 0 or epoch == args.epochs - 1:
            print("epoch %2d  mse %.4f" % (epoch, mse))
    print("done in %.1fs  mse %.4f -> %.4f" % (time.time() - t0,
                                               first, last))
    assert last < first * 0.2, "matrix factorization failed to converge"


if __name__ == "__main__":
    main()
