"""Export a trained model to a StableHLO artifact and serve predictions.

The artifact (``deploy.export_model``) contains the COMPILED forward —
weights baked in, shapes checked at load — and is the rebuild's answer
to the reference's C predict API: any PJRT runtime can execute it; here
``deploy.Predictor`` is the in-process loader.

Usage:
    python examples/deploy/export_and_serve.py
"""
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import nd, deploy
from mxnet_tpu.gluon.model_zoo import vision


def main():
    mx.random.seed(0)
    net = vision.resnet18_v1()
    net.initialize(mx.init.Xavier())
    x = nd.array(np.random.RandomState(0).rand(1, 3, 64, 64)
                 .astype(np.float32))
    ref = net(x)

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "resnet18.mxtpu")
        meta = deploy.export_model(net, (x,), path)
        print("exported %s: %d bytes, platforms=%s"
              % (path, os.path.getsize(path), meta["platforms"]))

        pred = deploy.Predictor(path)
        out = pred.predict(x)
        err = float(np.abs(out.asnumpy() - ref.asnumpy()).max())
        print("artifact vs live model max err: %.2e" % err)
        print("top-1 class:", int(out.asnumpy().argmax()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
