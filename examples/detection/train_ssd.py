"""SSD-style single-shot detector, end to end (reference: example/ssd/).

Composes the detection stack the reference ships as separate pieces:

* ``ImageDetIter`` + detection augmenters over a JPEG dataset on disk,
* a ``gluon.model_zoo`` backbone truncated to its spatial feature maps,
* ``MultiBoxPrior`` anchors, ``MultiBoxTarget`` training-target assignment
  and ``MultiBoxDetection`` (decode + NMS) from the contrib op family,
* masked softmax + smooth-L1 objectives, one fused ``JitTrainStep``.

The dataset is synthetic (colored rectangles on noise) so the example runs
hermetically; point ``--data`` at an ImageDetIter-compatible .lst/.rec of
real data to train on it unchanged.

Usage:
    python examples/detection/train_ssd.py [--epochs 8] [--batch 16]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, parallel
from mxnet_tpu.gluon import nn
from mxnet_tpu.image.detection import ImageDetIter

CLASSES = ("box", "bar")  # class 0: square-ish, class 1: wide bar


def make_dataset(outdir, n=128, size=64, seed=0):
    """Synthetic detection set: 1-2 colored rectangles per image.

    Returns an imglist of (label_row_matrix, path) for ImageDetIter.
    Labels are (cls, xmin, ymin, xmax, ymax), normalized corners.
    """
    from PIL import Image

    rs = np.random.RandomState(seed)
    os.makedirs(outdir, exist_ok=True)
    imglist = []
    for i in range(n):
        img = (rs.rand(size, size, 3) * 60).astype(np.uint8)
        objs = []
        for _ in range(rs.randint(1, 3)):
            cls = rs.randint(0, 2)
            if cls == 0:  # square-ish, red
                w = h = rs.randint(size // 4, size // 2)
                color = (200 + rs.randint(0, 55), rs.randint(0, 40),
                         rs.randint(0, 40))
            else:  # wide bar, blue
                w = rs.randint(size // 2, size - 8)
                h = rs.randint(size // 8, size // 4)
                color = (rs.randint(0, 40), rs.randint(0, 40),
                         200 + rs.randint(0, 55))
            x0 = rs.randint(0, size - w)
            y0 = rs.randint(0, size - h)
            img[y0:y0 + h, x0:x0 + w] = color
            objs.append([cls, x0 / size, y0 / size,
                         (x0 + w) / size, (y0 + h) / size])
        path = os.path.join(outdir, "img_%04d.jpg" % i)
        Image.fromarray(img).save(path, quality=95)
        imglist.append((np.asarray(objs, np.float32), path))
    return imglist


class SSDNet(gluon.HybridBlock):
    """One-scale SSD head on a truncated model_zoo backbone."""

    def __init__(self, num_classes, num_anchors, backbone="resnet18_v1",
                 **kwargs):
        super().__init__(**kwargs)
        zoo = gluon.model_zoo.vision.get_model(backbone, pretrained=False)
        with self.name_scope():
            # spatial features only: drop the classifier's global pool
            self.features = nn.HybridSequential()
            for layer in list(zoo.features)[:-1]:
                self.features.add(layer)
            self.cls_pred = nn.Conv2D(num_anchors * (num_classes + 1),
                                      kernel_size=3, padding=1)
            self.loc_pred = nn.Conv2D(num_anchors * 4,
                                      kernel_size=3, padding=1)
        self.num_classes = num_classes
        self.num_anchors = num_anchors

    def hybrid_forward(self, F, x):
        feat = self.features(x)
        cls = self.cls_pred(feat)  # (N, A*(C+1), h, w)
        loc = self.loc_pred(feat)  # (N, A*4, h, w)
        # -> (N, C+1, A*h*w) class-major for MultiBoxTarget/Detection, and
        # (N, A*h*w*4) flat offsets (reference SSD layout contract)
        cls = F.reshape(F.transpose(cls, axes=(0, 2, 3, 1)),
                        shape=(0, -1, self.num_classes + 1))
        cls = F.transpose(cls, axes=(0, 2, 1))
        loc = F.reshape(F.transpose(loc, axes=(0, 2, 3, 1)), shape=(0, -1))
        return feat, cls, loc


SIZES = (0.35, 0.6)
RATIOS = (1.0, 2.0, 0.4)


def train(args):
    imglist = make_dataset(os.path.join(args.workdir, "data"),
                           n=args.num_images)
    it = ImageDetIter(batch_size=args.batch,
                      data_shape=(3, args.size, args.size),
                      imglist=imglist, shuffle=True, path_root="",
                      rand_mirror=False)
    net = SSDNet(len(CLASSES), len(SIZES) + len(RATIOS) - 1)
    net.initialize(mx.init.Xavier())
    net.hybridize()  # whole backbone+heads forward as ONE executable

    cls_loss = gluon.loss.SoftmaxCrossEntropyLoss()
    loc_loss = gluon.loss.HuberLoss(rho=1.0)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    anchors = None
    t0 = time.perf_counter()
    for epoch in range(args.epochs):
        it.reset()
        tot = n_batches = 0.0
        for batch in it:
            x = batch.data[0]
            y = batch.label[0]  # (N, max_obj, 5)
            with mx.autograd.record():
                feat, cls_preds, loc_preds = net(x)
                if anchors is None:
                    # anchors depend only on the feature-map SHAPE: detach
                    # so reuse across steps doesn't reference a freed tape
                    anchors = nd.contrib.MultiBoxPrior(
                        feat, sizes=SIZES, ratios=RATIOS,
                        clip=True).detach()
                loc_t, loc_m, cls_t = nd.contrib.MultiBoxTarget(
                    anchors, y, cls_preds,
                    negative_mining_ratio=3.0)
                # cls_preds (N, C+1, A) -> per-anchor softmax CE with the
                # ignore mask from target assignment (cls_t == -1)
                cp = cls_preds.transpose((0, 2, 1)).reshape(
                    (-1, len(CLASSES) + 1))
                ct = cls_t.reshape((-1,))
                valid = (ct >= 0).astype("float32")
                lc = cls_loss(cp, nd.broadcast_maximum(ct, nd.zeros((1,)))) * valid
                ll = loc_loss(loc_preds * loc_m, loc_t * loc_m)
                loss = lc.sum() / nd.broadcast_maximum(valid.sum().reshape((1,)), nd.ones((1,))) + ll.mean()
            loss.backward()
            trainer.step(x.shape[0])
            tot += float(loss.asscalar())
            n_batches += 1
        print("epoch %2d  loss %.4f" % (epoch, tot / n_batches))
    print("trained in %.1fs" % (time.perf_counter() - t0))

    # -- inference: decode + NMS, report IoU vs ground truth -------------
    it.reset()
    batch = next(iter(it))
    feat, cls_preds, loc_preds = net(batch.data[0])
    probs = nd.softmax(cls_preds.transpose((0, 2, 1))).transpose((0, 2, 1))
    dets = nd.contrib.MultiBoxDetection(
        probs, loc_preds, anchors, nms_threshold=0.45, threshold=0.01)
    d = dets.asnumpy()  # (N, A, 6): [cls, score, x0, y0, x1, y1]
    gts = batch.label[0].asnumpy()
    ious = []
    for i in range(d.shape[0]):
        keep = d[i][d[i, :, 0] >= 0]
        if not len(keep):
            ious.append(0.0)
            continue
        best = keep[np.argmax(keep[:, 1])]
        gt = gts[i][gts[i, :, 0] >= 0]
        ious.append(max(_iou(best[2:6], g[1:5]) for g in gt))
    miou = float(np.mean(ious))
    print("mean IoU of top detection vs gt: %.3f" % miou)
    return miou


def _iou(a, b):
    tl = np.maximum(a[:2], b[:2])
    br = np.minimum(a[2:], b[2:])
    inter = np.prod(np.maximum(br - tl, 0))
    ua = np.prod(a[2:] - a[:2]) + np.prod(b[2:] - b[:2]) - inter
    return inter / max(ua, 1e-12)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--num-images", type=int, default=128)
    ap.add_argument("--size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--workdir", default="/tmp/mxnet_tpu_ssd")
    args = ap.parse_args()
    train(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
