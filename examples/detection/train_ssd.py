"""SSD-style single-shot detector, end to end (reference: example/ssd/).

Composes the detection stack the reference ships as separate pieces:

* ``ImageDetIter`` + detection augmenters over a JPEG dataset on disk,
* a ``gluon.model_zoo`` backbone truncated to its spatial feature maps
  (built under the global layout policy — channels-last on TPU),
* ``MultiBoxPrior`` anchors, ``MultiBoxTarget`` training-target assignment
  and ``MultiBoxDetection`` (decode + NMS) from the contrib op family,
* masked softmax + smooth-L1 objectives, with the ENTIRE train step
  (forward, target assignment, losses, backward, Adam) compiled into one
  executable via ``parallel.JitTrainStep``.

The dataset is synthetic (colored rectangles on noise) so the example runs
hermetically; point ``ImageDetIter`` at a .lst/.rec of real data to train
on it unchanged.

Usage:
    python examples/detection/train_ssd.py [--epochs 20] [--batch 16]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import gluon, layout as layout_mod, nd, parallel
from mxnet_tpu.gluon import nn
from mxnet_tpu.image.detection import ImageDetIter

CLASSES = ("box", "bar")  # class 0: square-ish, class 1: wide bar
SIZES = (0.3, 0.55, 0.8)
RATIOS = (1.0, 2.0, 0.5)
NUM_ANCHORS = len(SIZES) + len(RATIOS) - 1


def make_dataset(outdir, n=128, size=64, seed=0):
    """Synthetic detection set: 1-2 colored rectangles per image.

    Returns an imglist of (label_row_matrix, path) for ImageDetIter.
    Labels are (cls, xmin, ymin, xmax, ymax), normalized corners.
    """
    from PIL import Image

    rs = np.random.RandomState(seed)
    os.makedirs(outdir, exist_ok=True)
    imglist = []
    for i in range(n):
        img = (rs.rand(size, size, 3) * 60).astype(np.uint8)
        objs = []
        for _ in range(rs.randint(1, 3)):
            cls = rs.randint(0, 2)
            if cls == 0:  # square-ish, red
                w = h = rs.randint(size // 4, size // 2)
                color = (200 + rs.randint(0, 55), rs.randint(0, 40),
                         rs.randint(0, 40))
            else:  # wide bar, blue
                w = rs.randint(size // 2, size - 8)
                h = rs.randint(size // 8, size // 4)
                color = (rs.randint(0, 40), rs.randint(0, 40),
                         200 + rs.randint(0, 55))
            x0 = rs.randint(0, size - w)
            y0 = rs.randint(0, size - h)
            img[y0:y0 + h, x0:x0 + w] = color
            objs.append([cls, x0 / size, y0 / size,
                         (x0 + w) / size, (y0 + h) / size])
        path = os.path.join(outdir, "img_%04d.jpg" % i)
        Image.fromarray(img).save(path, quality=95)
        imglist.append((np.asarray(objs, np.float32), path))
    return imglist


class SSDNet(gluon.HybridBlock):
    """One-scale SSD head on a truncated model_zoo backbone.

    Follows the model-zoo layout idiom (`vision/_base.py`): layers are
    built under the policy layout (NHWC on TPU), the public input contract
    stays NCHW, and the head reshapes are layout-aware.
    """

    def __init__(self, num_classes, num_anchors, backbone="resnet18_v1",
                 cut=6, **kwargs):
        super().__init__(**kwargs)
        self._layout = layout_mod.preferred_layout(2)
        self._channel_last = not self._layout.startswith("NC")
        zoo = gluon.model_zoo.vision.get_model(backbone, pretrained=False)
        with layout_mod.layout_scope(self._layout), self.name_scope():
            # stem + first two residual stages: a 64px input keeps an
            # 8x8 spatial map (deeper stages collapse it to 2x2)
            self.features = nn.HybridSequential()
            for layer in list(zoo.features)[:cut]:
                self.features.add(layer)
            self.cls_pred = nn.Conv2D(num_anchors * (num_classes + 1),
                                      kernel_size=3, padding=1)
            self.loc_pred = nn.Conv2D(num_anchors * 4,
                                      kernel_size=3, padding=1)
        self.num_classes = num_classes
        self.num_anchors = num_anchors

    def hybrid_forward(self, F, x):
        if self._channel_last:
            x = F.transpose(x, axes=(0, 2, 3, 1))  # NCHW contract -> NHWC
        feat = self.features(x)
        cls = self.cls_pred(feat)
        loc = self.loc_pred(feat)
        if not self._channel_last:  # NCHW: channels to the minor dim first
            feat = F.transpose(feat, axes=(0, 2, 3, 1))
            cls = F.transpose(cls, axes=(0, 2, 3, 1))
            loc = F.transpose(loc, axes=(0, 2, 3, 1))
        b = x.shape[0]
        # rows ordered (h, w, anchor) to match MultiBoxPrior's layout
        cls = F.transpose(F.reshape(cls, shape=(b, -1,
                                                self.num_classes + 1)),
                          axes=(0, 2, 1))       # (B, C+1, h*w*A)
        loc = F.reshape(loc, shape=(b, -1))     # (B, h*w*A*4)
        # NCHW-shaped carrier for MultiBoxPrior (reads shape[2], shape[3])
        feat_sh = F.transpose(feat, axes=(0, 3, 1, 2))
        return feat_sh, cls, loc


class SSDTrainLoss(gluon.HybridBlock):
    """Forward + target assignment + masked objectives as ONE graph.

    ``JitTrainStep(net, loss=None)`` compiles this whole block — backbone,
    anchor matching, hard-negative mining, both losses, backward and the
    optimizer — into a single XLA executable per step.
    """

    def __init__(self, ssd, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ssd = ssd

    def hybrid_forward(self, F, x, label):
        feat_sh, cls_preds, loc_preds = self.ssd(x)
        anchors = F.contrib.MultiBoxPrior(
            feat_sh, sizes=SIZES, ratios=RATIOS, clip=True)
        loc_t, loc_m, cls_t = F.contrib.MultiBoxTarget(
            F.BlockGrad(anchors), label, F.BlockGrad(cls_preds),
            negative_mining_ratio=3.0)
        nc = self.ssd.num_classes
        # per-anchor softmax CE with the ignore mask (cls_t == -1)
        cp = F.reshape(F.transpose(cls_preds, axes=(0, 2, 1)),
                       shape=(-1, nc + 1))
        ct = F.reshape(cls_t, shape=(-1,))
        valid = F.BlockGrad((ct >= 0).astype('float32'))
        tgt = F.BlockGrad(F.relu(ct))  # clamp ignored (-1) to 0 for pick
        logp = F.log_softmax(cp, axis=-1)
        lc = -F.pick(logp, tgt, axis=-1) * valid
        ls = F.smooth_l1(loc_preds * loc_m - loc_t * loc_m, scalar=1.0)
        denom = F.broadcast_maximum(F.reshape(F.sum(valid), shape=(1,)),
                                    F.ones(shape=(1,)))
        return F.sum(lc) / denom + F.mean(F.sum(ls, axis=-1)) / 100.0


def train(args):
    imglist = make_dataset(os.path.join(args.workdir, "data"),
                           n=args.num_images)
    it = ImageDetIter(batch_size=args.batch,
                      data_shape=(3, args.size, args.size),
                      imglist=imglist, shuffle=True, path_root="")
    net = SSDNet(len(CLASSES), NUM_ANCHORS)
    net.initialize(mx.init.Xavier())
    step = parallel.JitTrainStep(SSDTrainLoss(net), None, "adam",
                                 {"learning_rate": args.lr})

    t0 = time.perf_counter()
    for epoch in range(args.epochs):
        it.reset()
        tot = n_batches = 0.0
        for batch in it:
            loss = step.step(batch.data[0], batch.label[0])
            tot += float(loss)
            n_batches += 1
        if epoch % 5 == 0 or epoch == args.epochs - 1:
            print("epoch %2d  loss %.4f" % (epoch, tot / n_batches))
    print("trained in %.1fs" % (time.perf_counter() - t0))
    step.sync_params()

    # -- inference: decode + NMS, report IoU vs ground truth -------------
    it.reset()
    batch = next(iter(it))
    # params live on the training device after sync_params; bring the
    # eval batch to them (eager ops need one committed device)
    from mxnet_tpu.context import _best_context

    feat_sh, cls_preds, loc_preds = net(
        batch.data[0].as_in_context(_best_context()))
    anchors = nd.contrib.MultiBoxPrior(feat_sh, sizes=SIZES, ratios=RATIOS,
                                       clip=True)
    probs = nd.softmax(cls_preds.transpose((0, 2, 1))).transpose((0, 2, 1))
    dets = nd.contrib.MultiBoxDetection(
        probs, loc_preds, anchors, nms_threshold=0.45, threshold=0.01)
    d = dets.asnumpy()  # (N, A, 6): [cls, score, x0, y0, x1, y1]
    gts = batch.label[0].asnumpy()
    ious = []
    for i in range(d.shape[0]):
        keep = d[i][d[i, :, 0] >= 0]
        if not len(keep):
            ious.append(0.0)
            continue
        best = keep[np.argmax(keep[:, 1])]
        gt = gts[i][gts[i, :, 0] >= 0]
        ious.append(max(_iou(best[2:6], g[1:5]) for g in gt))
    miou = float(np.mean(ious))
    print("mean IoU of top detection vs gt: %.3f" % miou)
    return miou


def _iou(a, b):
    tl = np.maximum(a[:2], b[:2])
    br = np.minimum(a[2:], b[2:])
    inter = np.prod(np.maximum(br - tl, 0))
    ua = np.prod(a[2:] - a[:2]) + np.prod(b[2:] - b[:2]) - inter
    return inter / max(ua, 1e-12)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=20)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--num-images", type=int, default=128)
    ap.add_argument("--size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--workdir", default="/tmp/mxnet_tpu_ssd")
    args = ap.parse_args()
    train(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
