#!/usr/bin/env python
"""Profile a training loop: chrome-trace dump + aggregate-stats table.

Parity with the reference's ``example/profiler`` scripts
(``profiler_executor.py``/``profiler_ndarray.py``: set_config →
set_state('run') → work → set_state('stop') → dump, plus custom
Domain/Task instrumentation).  Produces:

- a chrome://tracing-loadable JSON (``--out``, default
  ``profile_train.json``),
- the per-op aggregate table on stdout (``mx.profiler.dumps()`` — the
  reference's MXDumpAggregateStats path),
- a custom domain span + counter showing user instrumentation
  (``mx.profiler.Domain`` / ``Task`` / ``Counter``).

    python examples/profiler/profile_training.py [--steps 20]

On TPU the per-op spans come from the engine's dispatch hook; the XLA
device timeline itself is captured separately with
``tools/profile_resnet.py`` (xplane).  This example profiles the
FRAMEWORK level: op dispatch, custom task spans, counters.
"""
from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
from examples import _device_setup  # noqa: E402

_device_setup.ensure_devices(1)

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon, nd  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--out", default="profile_train.json")
    args = ap.parse_args()

    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(128, activation="relu"),
            gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})

    rs = np.random.RandomState(0)
    x = nd.array(rs.randn(64, 32).astype(np.float32))
    y = nd.array(rs.randint(0, 10, 64).astype(np.float32))

    mx.profiler.set_config(profile_all=True, filename=args.out,
                           aggregate_stats=True)
    domain = mx.profiler.Domain("example")
    counter = domain.new_counter("samples_seen", 0)

    mx.profiler.set_state("run")
    epoch_task = domain.new_task("training")
    epoch_task.start()
    last = None
    for _ in range(args.steps):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(batch_size=64)
        counter.increment(64)
        last = loss
    print("final loss: %.4f" % float(last.mean().asscalar()))
    epoch_task.stop()
    mx.profiler.set_state("stop")

    print(mx.profiler.dumps(format="table", sort_by="total"))
    mx.profiler.dump()
    size = os.path.getsize(args.out)
    print("chrome trace written: %s (%d bytes) — load in "
          "chrome://tracing or perfetto" % (args.out, size))
    assert size > 0


if __name__ == "__main__":
    main()
