"""LSTM + CTC sequence recognition (reference: example/ctc/lstm_ocr.py).

Exercises the CTC surface end to end: a recurrent encoder over a synthetic
"stripe OCR" task (each image column belongs to a digit-stripe or blank),
``gluon.loss.CTCLoss`` (alignment-free), and greedy CTC decoding with
blank/duplicate collapse — the pipeline the reference's captcha/OCR
examples are built on.

Task: sequences of 3 "glyphs" (vertical stripe patterns) of variable
width, rendered into a (W, H) image; the model reads columns left to
right and must output the glyph ids.

Usage:
    python examples/ctc/train_ctc.py [--epochs 10]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn, rnn

N_CLASSES = 4       # glyph ids 1..4 (0 is the CTC blank)
SEQ_GLYPHS = 3
HEIGHT = 8
WIDTH = 24


def render(rs, n):
    """(n, WIDTH, HEIGHT) images + (n, SEQ_GLYPHS) labels (1-based)."""
    imgs = np.zeros((n, WIDTH, HEIGHT), np.float32)
    labels = np.zeros((n, SEQ_GLYPHS), np.float32)
    for i in range(n):
        col = 1
        for j in range(SEQ_GLYPHS):
            g = rs.randint(1, N_CLASSES + 1)
            labels[i, j] = g - 1  # 0-based class ids; blank is LAST (=4)
            w = rs.randint(3, 6)
            # glyph g = stripe pattern: rows [0:2g] lit
            imgs[i, col:col + w, 0:2 * g] = 1.0
            col += w + rs.randint(1, 3)  # gap
    imgs += rs.randn(n, WIDTH, HEIGHT).astype(np.float32) * 0.05
    return imgs, labels


class CTCNet(gluon.Block):
    def __init__(self, hidden=48, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.lstm = rnn.LSTM(hidden, num_layers=1, layout="NTC")
            self.proj = nn.Dense(N_CLASSES + 1, flatten=False)

    def forward(self, x):  # x: (N, T, H)
        return self.proj(self.lstm(x))  # (N, T, C+1)


def greedy_decode(logits):
    """argmax -> collapse duplicates -> drop blanks (CTC best path).

    gluon.loss.CTCLoss uses blank_label='last': real classes are
    0..N_CLASSES-1 and the blank is index N_CLASSES."""
    ids = logits.argmax(-1)
    outs = []
    for row in ids:
        seq, prev = [], -1
        for t in row:
            if t != prev and t != N_CLASSES:
                seq.append(int(t))
            prev = t
        outs.append(seq)
    return outs


def train(args):
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    net = CTCNet()
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.CTCLoss(layout="NTC", label_layout="NT")
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-3})

    t0 = time.perf_counter()
    for epoch in range(args.epochs):
        tot = 0.0  # device scalar after first add; pulled once per epoch
        for _ in range(args.iters):
            x, y = render(rs, args.batch)
            with autograd.record():
                logits = net(nd.array(x))
                loss = loss_fn(logits, nd.array(y)).mean()
            loss.backward()
            trainer.step(args.batch)
            tot = loss + tot  # device-side accumulate, no per-batch sync
        if epoch % 3 == 0 or epoch == args.epochs - 1:
            # one intentional pull per logged epoch  # mxlint: allow-host-sync
            print("epoch %2d  ctc loss %.4f" % (epoch, float(tot.asscalar()) / args.iters))
    print("trained in %.1fs" % (time.perf_counter() - t0))

    # exact-sequence accuracy with greedy decoding
    x, y = render(rs, 64)
    logits = net(nd.array(x)).asnumpy()
    decoded = greedy_decode(logits)
    acc = np.mean([list(map(int, yy)) == d
                   for yy, d in zip(y, decoded)])
    print("greedy exact-sequence accuracy: %.3f" % acc)
    return float(acc)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--batch", type=int, default=32)
    args = ap.parse_args()
    train(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
