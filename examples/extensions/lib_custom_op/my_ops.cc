// Example operator plugin (parity: example/extensions/lib_custom_op).
// Implements two ops with zero framework linkage:
//   my_gelu  — tanh-approx GELU, with an analytic backward
//   my_relu6 — clip(x, 0, 6), forward-only
//
// Build:  g++ -O2 -shared -fPIC -std=c++17 my_ops.cc -o libmyops.so
// Load:   mx.library.load("libmyops.so")

#include <cmath>
#include <cstring>

namespace {

long numel(const long* shape, int ndim) {
  long n = 1;
  for (int i = 0; i < ndim; ++i) n *= shape[i];
  return n;
}

constexpr float kC = 0.7978845608028654f;  // sqrt(2/pi)

}  // namespace

extern "C" {

int mx_plugin_abi_version() { return 1; }
long mx_plugin_num_ops() { return 2; }

const char* mx_plugin_op_name(long i) {
  return i == 0 ? "my_gelu" : "my_relu6";
}

long mx_plugin_op_num_inputs(long i) { return 1; }

int mx_plugin_op_has_backward(long i) { return i == 0 ? 1 : 0; }

int mx_plugin_op_infer_shape(long, const long* const* in_shapes,
                             const int* in_ndims, long,
                             long* out_shape, int* out_ndim) {
  *out_ndim = in_ndims[0];
  std::memcpy(out_shape, in_shapes[0], sizeof(long) * in_ndims[0]);
  return 0;
}

int mx_plugin_op_forward(long i, const float* const* inputs,
                         const long* const* in_shapes,
                         const int* in_ndims, long,
                         float* output, const long* out_shape,
                         int out_ndim) {
  const float* x = inputs[0];
  const long n = numel(out_shape, out_ndim);
  if (i == 0) {
    for (long j = 0; j < n; ++j) {
      const float v = x[j];
      output[j] = 0.5f * v * (1.0f + std::tanh(kC * (v + 0.044715f * v * v * v)));
    }
  } else {
    for (long j = 0; j < n; ++j) {
      float v = x[j];
      output[j] = v < 0.f ? 0.f : (v > 6.f ? 6.f : v);
    }
  }
  return 0;
}

int mx_plugin_op_backward(long i, const float* const* inputs,
                          const long* const* in_shapes,
                          const int* in_ndims, long,
                          const float* out_grad, float* const* in_grads) {
  if (i != 0) return -1;
  const float* x = inputs[0];
  const long n = numel(in_shapes[0], in_ndims[0]);
  for (long j = 0; j < n; ++j) {
    const float v = x[j];
    const float u = kC * (v + 0.044715f * v * v * v);
    const float t = std::tanh(u);
    const float du = kC * (1.0f + 3.0f * 0.044715f * v * v);
    in_grads[0][j] = out_grad[j] *
        (0.5f * (1.0f + t) + 0.5f * v * (1.0f - t * t) * du);
  }
  return 0;
}

}  // extern "C"
