"""Bucketing LSTM language model — the reference's iconic RNN workflow
(example/rnn/bucketing/lstm_bucketing.py) on the TPU-native stack:

  mx.rnn.BucketSentenceIter  ->  per-bucket symbol graphs from
  mx.rnn.FusedRNNCell (the monolithic RNN op = one fused lax.scan chain)
  ->  mx.mod.BucketingModule.fit (one compiled executable per bucket,
  shared parameter arrays).

Runs on CPU out of the box with a tiny synthetic corpus.
Run: python examples/rnn/bucketing_lm.py
"""
import os
import random
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

from _device_setup import ensure_devices  # noqa: E402

ensure_devices(1)

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import rnn  # noqa: E402

VOCAB = 40
HIDDEN = 32
EMBED = 16
BATCH = 8
BUCKETS = [6, 10, 14]


def synthetic_corpus(n=400, seed=0):
    """Token sequences with a learnable pattern (next = (tok + 1) % V
    with noise) in assorted lengths."""
    rng = random.Random(seed)
    sents = []
    for _ in range(n):
        length = rng.choice([5, 6, 8, 9, 12, 13])
        start = rng.randrange(2, VOCAB)
        sent = [(start + i) % (VOCAB - 2) + 2 for i in range(length)]
        sents.append(sent)
    return sents


def sym_gen(seq_len):
    data = mx.sym.var("data")
    label = mx.sym.var("softmax_label")
    embed = mx.sym.Embedding(data, input_dim=VOCAB, output_dim=EMBED,
                             name="embed")
    cell = rnn.FusedRNNCell(HIDDEN, num_layers=1, mode="lstm",
                            prefix="lstm_")
    outputs, _ = cell.unroll(seq_len, inputs=embed, layout="NTC",
                             merge_outputs=True)
    pred = mx.sym.reshape(outputs, shape=(-1, HIDDEN))
    pred = mx.sym.FullyConnected(pred, num_hidden=VOCAB, name="pred")
    label_flat = mx.sym.reshape(label, shape=(-1,))
    loss = mx.sym.SoftmaxOutput(pred, label_flat, name="softmax")
    return loss, ("data",), ("softmax_label",)


def main():
    sents = synthetic_corpus()
    it = rnn.BucketSentenceIter(sents, BATCH, buckets=BUCKETS,
                                invalid_label=0)
    mod = mx.module.BucketingModule(
        sym_gen, default_bucket_key=it.default_bucket_key)
    metric = mx.metric.Perplexity(ignore_label=0)
    mod.fit(it, eval_metric=metric, num_epoch=3,
            optimizer="adam",
            optimizer_params={"learning_rate": 5e-3},
            initializer=mx.init.Xavier())
    # final perplexity after training
    it.reset()
    metric.reset()
    for batch in it:
        mod.forward(batch, is_train=False)
        mod.update_metric(metric, batch.label)
    name, value = metric.get()
    print("final %s: %.2f" % (name, value))
    assert np.isfinite(value)


if __name__ == "__main__":
    main()
